"""Ring attention: exact causal attention over a sequence-sharded axis.

Long-context / context-parallel support (SURVEY.md §5.7 notes the reference
has none — sequence length there is scaled only by seq-len sweeps; this is
the capability that lets the TPU build go past a single chip's HBM).  The
idea (Liu et al., "Ring Attention with Blockwise Transformers", 2023; see
PAPERS.md): shard the sequence across a mesh axis, keep queries resident,
and circulate K/V blocks around the ring with ``ppermute`` while each
device folds every visiting block into a flash-style online-softmax
accumulator.  No device ever holds more than one remote KV block, so
attention memory is O(S_local · S_block) instead of O(S²), and each hop's
transfer overlaps the previous block's compute on ICI.

Semantics are EXACT full causal attention over the global sequence —
verified against the monolithic fp32 reference in tests — not an
approximation.  Numerics: scores and the (m, l, o) accumulator run in
fp32 regardless of input dtype (the same policy as ``_attention_xla``).

Causal note: with naive contiguous sharding, later ranks do more useful
work per hop than earlier ranks (rank 0 masks everything but its own
block).  The program is SPMD so the wall-clock cost is the full ring
either way; zigzag/striped layouts that rebalance this are a known
refinement and deliberately out of scope here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _block_scores(q, k, scale):
    """(B, Sq, n, hd) × (B, Skv, n, hd) → fp32 (B, n, Sq, Skv)."""
    return jnp.einsum("bqnh,bknh->bnqk", q, k,
                      preferred_element_type=jnp.float32) * scale


def ring_attention(q, k, v, axis_name: str, *, scale: float,
                   causal: bool = True,
                   block_q: int | None = None) -> jax.Array:
    """Attention over a sequence sharded on ``axis_name`` (shard_map only).

    q, k, v: (B, S_local, n_heads, head_dim) — this device's contiguous
    chunk of the global sequence, chunks laid out in rank order.  GQA
    inputs (n_kv < n_q) are repeated up front.  Returns (B, S_local,
    n_heads, head_dim) in q's dtype.

    ``block_q``: chunk the query rows of each fold so the fp32 score
    buffer is (B, n, block_q, S_local) instead of (B, n, S_local,
    S_local) — the flash-style memory bound that makes long LOCAL chunks
    viable (at S_local=8k, nq=16 the unchunked buffer is 4 GB fp32 per
    hop).  Must divide S_local; None/0 = unchunked.
    """
    n_dev = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, Sq, nq, hd = q.shape
    nkv = k.shape[2]
    rep = nq // nkv  # GQA: repeat per-block at compute time — the ring
    qf = q.astype(jnp.float32)  # carries (and ships) only the nkv heads

    if block_q is not None and block_q <= 0:
        raise ValueError(f"block_q={block_q} must be a positive divisor "
                         f"of S_local={Sq} (or None)")
    Cq = block_q if block_q and block_q < Sq else Sq
    if Sq % Cq:
        raise ValueError(f"block_q={block_q} must divide S_local={Sq}")
    n_chunks = Sq // Cq
    rows = jnp.arange(Cq)
    cols = jnp.arange(Sq)
    if n_chunks > 1:
        # chunk-major query layout, computed ONCE — m/l/o are carried
        # chunk-major through the whole ring and reassembled at the end.
        qx = qf.reshape(B, n_chunks, Cq, nq, hd).transpose(1, 0, 2, 3, 4)
        offs = jnp.arange(n_chunks) * Cq

    # Ring: device i sends to i+1, so after t hops we hold block (my - t).
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def merge_chunk(src, off, qc, k_blk, v_blk, m, l, o):
        """Online-softmax merge of one KV block into one q-chunk's
        (m, l, o).  ``off`` = the chunk's first row within the local
        sequence; shapes: qc/o (B, Cq, n, hd), m/l (B, n, Cq, 1)."""
        s = _block_scores(qc, k_blk, scale)                   # (B,n,Cq,Skv)
        if causal:
            # Global causality across contiguous blocks: earlier block ->
            # fully visible, own block -> lower triangle, later -> nothing.
            diag = cols[None, :] <= (off + rows)[:, None]     # (Cq, Skv)
            blk = jnp.where(src == my, diag, src < my)
            s = jnp.where(blk[None, None], s, _NEG_INF)
        m_blk = jnp.max(s, axis=-1, keepdims=True)            # (B,n,Cq,1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new)
        # A fully-masked block (src > my) must contribute zero even though
        # exp(-inf - -inf) would be 1 when m_new is still -inf.
        p = jnp.where(m_new <= _NEG_INF, 0.0, p)
        corr = jnp.where(m <= _NEG_INF, 0.0, jnp.exp(m - m_new))
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        o = o * corr.swapaxes(1, 2) + jnp.einsum("bnqk,bknh->bqnh", p, v_blk)
        return m_new, l, o

    def fold_block(src, k_blk, v_blk, m, l, o):
        """Merge one visiting KV block into the local (m, l, o) —
        carried chunk-major ((n_chunks, B, ...) leading dim) when
        block_q is set, so the per-hop scan keeps only one chunk's score
        buffer live and no relayout happens inside the ring."""
        k_blk = k_blk.astype(jnp.float32)
        v_blk = v_blk.astype(jnp.float32)
        if rep != 1:
            k_blk = jnp.repeat(k_blk, rep, axis=2)
            v_blk = jnp.repeat(v_blk, rep, axis=2)
        if n_chunks == 1:
            return merge_chunk(src, 0, qf, k_blk, v_blk, m, l, o)

        def body(_, xs):
            qc, mc, lc, oc, off = xs
            return None, merge_chunk(src, off, qc, k_blk, v_blk,
                                     mc, lc, oc)

        _, out = lax.scan(body, None, (qx, m, l, o, offs))
        return out

    def fold(carry, t):
        # Permute at iteration START: n_dev-1 hops total, no dead final
        # transfer (the local block is folded outside the scan).
        k_blk, v_blk, m, l, o = carry
        k_blk, v_blk = jax.tree.map(
            lambda x: lax.ppermute(x, axis_name, perm), (k_blk, v_blk))
        m, l, o = fold_block((my - t) % n_dev, k_blk, v_blk, m, l, o)
        return (k_blk, v_blk, m, l, o), None

    if n_chunks == 1:
        m0 = jnp.full((B, nq, Sq, 1), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, nq, Sq, 1), jnp.float32)
        o0 = jnp.zeros((B, Sq, nq, hd), jnp.float32)
    else:
        m0 = jnp.full((n_chunks, B, nq, Cq, 1), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((n_chunks, B, nq, Cq, 1), jnp.float32)
        o0 = jnp.zeros((n_chunks, B, Cq, nq, hd), jnp.float32)
    m, l, o = fold_block(my, k, v, m0, l0, o0)          # t = 0: own block
    if n_dev > 1:
        (_, _, _, l, o), _ = lax.scan(fold, (k, v, m, l, o),
                                      jnp.arange(1, n_dev))
    if n_chunks > 1:  # chunk-major -> (B, ...) once, after the ring
        l = l.transpose(1, 2, 0, 3, 4).reshape(B, nq, Sq, 1)
        o = o.transpose(1, 0, 2, 3, 4).reshape(B, Sq, nq, hd)
    l = jnp.where(l == 0.0, 1.0, l)  # rows with no visible keys (unused)
    return (o / l.swapaxes(1, 2)).astype(q.dtype)
