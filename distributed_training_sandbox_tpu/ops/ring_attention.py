"""Ring attention: exact causal attention over a sequence-sharded axis.

Long-context / context-parallel support (SURVEY.md §5.7 notes the reference
has none — sequence length there is scaled only by seq-len sweeps; this is
the capability that lets the TPU build go past a single chip's HBM).  The
idea (Liu et al., "Ring Attention with Blockwise Transformers", 2023; see
PAPERS.md): shard the sequence across a mesh axis, keep queries resident,
and circulate K/V blocks around the ring with ``ppermute`` while each
device folds every visiting block into a flash-style online-softmax
accumulator.  No device ever holds more than one remote KV block, so
attention memory is O(S_local · S_block) instead of O(S²), and each hop's
transfer overlaps the previous block's compute on ICI.

Semantics are EXACT full causal attention over the global sequence —
verified against the monolithic fp32 reference in tests — not an
approximation.  Numerics: scores and the (m, l, o) accumulator run in
fp32 regardless of input dtype (the same policy as ``_attention_xla``).

Causal note: with naive contiguous sharding, later ranks do more useful
work per hop than earlier ranks (rank 0 masks everything but its own
block).  The program is SPMD so the wall-clock cost is the full ring
either way; zigzag/striped layouts that rebalance this are a known
refinement and deliberately out of scope here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _block_scores(q, k, scale):
    """(B, Sq, n, hd) × (B, Skv, n, hd) → fp32 (B, n, Sq, Skv)."""
    return jnp.einsum("bqnh,bknh->bnqk", q, k,
                      preferred_element_type=jnp.float32) * scale


def ring_attention(q, k, v, axis_name: str, *, scale: float,
                   causal: bool = True) -> jax.Array:
    """Attention over a sequence sharded on ``axis_name`` (shard_map only).

    q, k, v: (B, S_local, n_heads, head_dim) — this device's contiguous
    chunk of the global sequence, chunks laid out in rank order.  GQA
    inputs (n_kv < n_q) are repeated up front.  Returns (B, S_local,
    n_heads, head_dim) in q's dtype.
    """
    n_dev = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, Sq, nq, hd = q.shape
    nkv = k.shape[2]
    rep = nq // nkv  # GQA: repeat per-block at compute time — the ring
    qf = q.astype(jnp.float32)  # carries (and ships) only the nkv heads

    # Ring: device i sends to i+1, so after t hops we hold block (my - t).
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
    tri = jnp.tril(jnp.ones((Sq, Sq), jnp.bool_))

    def fold_block(src, k_blk, v_blk, m, l, o):
        """Online-softmax merge of one visiting KV block into (m, l, o)."""
        k_blk = k_blk.astype(jnp.float32)
        v_blk = v_blk.astype(jnp.float32)
        if rep != 1:
            k_blk = jnp.repeat(k_blk, rep, axis=2)
            v_blk = jnp.repeat(v_blk, rep, axis=2)
        s = _block_scores(qf, k_blk, scale)
        if causal:
            # Global causality across contiguous blocks: earlier block ->
            # fully visible, own block -> lower triangle, later -> nothing.
            blk = jnp.where(src == my, tri, src < my)
            s = jnp.where(blk[None, None], s, _NEG_INF)
        m_blk = jnp.max(s, axis=-1, keepdims=True)            # (B,n,Sq,1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new)
        # A fully-masked block (src > my) must contribute zero even though
        # exp(-inf - -inf) would be 1 when m_new is still -inf.
        p = jnp.where(m_new <= _NEG_INF, 0.0, p)
        corr = jnp.where(m <= _NEG_INF, 0.0, jnp.exp(m - m_new))
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        o = o * corr.swapaxes(1, 2) + jnp.einsum("bnqk,bknh->bqnh", p, v_blk)
        return m_new, l, o

    def fold(carry, t):
        # Permute at iteration START: n_dev-1 hops total, no dead final
        # transfer (the local block is folded outside the scan).
        k_blk, v_blk, m, l, o = carry
        k_blk, v_blk = jax.tree.map(
            lambda x: lax.ppermute(x, axis_name, perm), (k_blk, v_blk))
        m, l, o = fold_block((my - t) % n_dev, k_blk, v_blk, m, l, o)
        return (k_blk, v_blk, m, l, o), None

    m0 = jnp.full((B, nq, Sq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, nq, Sq, 1), jnp.float32)
    o0 = jnp.zeros((B, Sq, nq, hd), jnp.float32)
    m, l, o = fold_block(my, k, v, m0, l0, o0)          # t = 0: own block
    if n_dev > 1:
        (_, _, _, l, o), _ = lax.scan(fold, (k, v, m, l, o),
                                      jnp.arange(1, n_dev))
    l = jnp.where(l == 0.0, 1.0, l)  # rows with no visible keys (unused)
    return (o / l.swapaxes(1, 2)).astype(q.dtype)
