"""Ring attention: exact causal attention over a sequence-sharded axis.

Long-context / context-parallel support (SURVEY.md §5.7 notes the reference
has none — sequence length there is scaled only by seq-len sweeps; this is
the capability that lets the TPU build go past a single chip's HBM).  The
idea (Liu et al., "Ring Attention with Blockwise Transformers", 2023; see
PAPERS.md): shard the sequence across a mesh axis, keep queries resident,
and circulate K/V blocks around the ring with ``ppermute`` while each
device folds every visiting block into a flash-style online-softmax
accumulator.  No device ever holds more than one remote KV block, so
attention memory is O(S_local · S_block) instead of O(S²), and each hop's
transfer overlaps the previous block's compute on ICI.

Semantics are EXACT full causal attention over the global sequence —
verified against the monolithic fp32 reference in tests — not an
approximation.  Numerics: scores and the (m, l, o) accumulator run in
fp32 regardless of input dtype (the same policy as ``_attention_xla``).

Causal note — two layouts:

  * "contiguous" (default): device r holds global rows
    [r·S_local, (r+1)·S_local).  Simple, but causally imbalanced: every
    hop computes a full S_local × S_local score block and then masks it
    (rank 0 masks everything but its own block) — at sp=D, ~half of all
    ring-hop score FLOPs are computed-then-discarded.
  * "zigzag": the global sequence is cut into 2D stripes of width
    W = S_local/2 and device r holds stripes (r, 2D−1−r) — an early and
    a late stripe.  Then for every REMOTE hop exactly two of the four
    stripe-pair products are visible, and both are FULLY visible (no
    mask): q_late × k_early always, plus q_early × k_early when
    src < my else q_late × k_late.  Per-hop useful work is uniform
    across ranks and the ring computes ~half the score FLOPs of the
    contiguous layout — the standard striped/zigzag rebalance (Llama-3
    context parallelism; zigzag ring attention).  Only the local block
    (t = 0) needs a mask, built from global stripe positions.

Zigzag requires the DATA laid out in stripe order — see
``parallel.sequence.zigzag_shuffle`` (loss means are permutation-
invariant, so training only needs ids/labels shuffled identically).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .collectives import axis_size

_NEG_INF = -1e30


def _block_scores(q, k, scale):
    """(B, Sq, n, hd) × (B, Skv, n, hd) → fp32 (B, n, Sq, Skv)."""
    return jnp.einsum("bqnh,bknh->bnqk", q, k,
                      preferred_element_type=jnp.float32) * scale


def ring_attention(q, k, v, axis_name: str, *, scale: float,
                   causal: bool = True,
                   block_q: int | None = None,
                   layout: str = "contiguous") -> jax.Array:
    """Attention over a sequence sharded on ``axis_name`` (shard_map only).

    q, k, v: (B, S_local, n_heads, head_dim) — this device's chunk of
    the global sequence: rank-order contiguous for ``layout=
    "contiguous"``, stripe pairs (my, 2D−1−my) for ``layout="zigzag"``
    (see module docstring; data must be pre-shuffled with
    ``parallel.sequence.zigzag_shuffle``).  GQA inputs (n_kv < n_q) are
    repeated per block.  Returns (B, S_local, n_heads, head_dim) in q's
    dtype.

    ``block_q``: chunk the query rows of each fold so the fp32 score
    buffer is (B, n, block_q, S_local) instead of (B, n, S_local,
    S_local) — the flash-style memory bound that makes long LOCAL chunks
    viable (at S_local=8k, nq=16 the unchunked buffer is 4 GB fp32 per
    hop).  Must divide S_local (S_local/2 for zigzag); None/0 =
    unchunked.
    """
    if layout == "zigzag":
        if not causal:
            raise ValueError("zigzag layout only pays off for causal "
                             "attention — use layout='contiguous'")
        return _ring_zigzag(q, k, v, axis_name, scale=scale,
                            block_q=block_q)
    if layout != "contiguous":
        raise ValueError(f"unknown ring layout {layout!r}")
    n_dev = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, Sq, nq, hd = q.shape
    nkv = k.shape[2]
    rep = nq // nkv  # GQA: repeat per-block at compute time — the ring
    qf = q.astype(jnp.float32)  # carries (and ships) only the nkv heads

    if block_q is not None and block_q <= 0:
        raise ValueError(f"block_q={block_q} must be a positive divisor "
                         f"of S_local={Sq} (or None)")
    if block_q and block_q > Sq:
        raise ValueError(f"block_q={block_q} exceeds S_local={Sq}; pass "
                         f"block_q=None (or <= S_local) — silently running "
                         f"unchunked would hide a misconfigured sp setup")
    Cq = block_q if block_q and block_q < Sq else Sq
    if Sq % Cq:
        raise ValueError(f"block_q={block_q} must divide S_local={Sq}")
    n_chunks = Sq // Cq
    rows = jnp.arange(Cq)
    cols = jnp.arange(Sq)
    if n_chunks > 1:
        # chunk-major query layout, computed ONCE — m/l/o are carried
        # chunk-major through the whole ring and reassembled at the end.
        qx = qf.reshape(B, n_chunks, Cq, nq, hd).transpose(1, 0, 2, 3, 4)
        offs = jnp.arange(n_chunks) * Cq

    # Ring: device i sends to i+1, so after t hops we hold block (my - t).
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def merge_chunk(src, off, qc, k_blk, v_blk, m, l, o):
        """Online-softmax merge of one KV block into one q-chunk's
        (m, l, o).  ``off`` = the chunk's first row within the local
        sequence; shapes: qc/o (B, Cq, n, hd), m/l (B, n, Cq, 1)."""
        s = _block_scores(qc, k_blk, scale)                   # (B,n,Cq,Skv)
        if causal:
            # Global causality across contiguous blocks: earlier block ->
            # fully visible, own block -> lower triangle, later -> nothing.
            diag = cols[None, :] <= (off + rows)[:, None]     # (Cq, Skv)
            blk = jnp.where(src == my, diag, src < my)
            s = jnp.where(blk[None, None], s, _NEG_INF)
        m_blk = jnp.max(s, axis=-1, keepdims=True)            # (B,n,Cq,1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new)
        # A fully-masked block (src > my) must contribute zero even though
        # exp(-inf - -inf) would be 1 when m_new is still -inf.
        p = jnp.where(m_new <= _NEG_INF, 0.0, p)
        corr = jnp.where(m <= _NEG_INF, 0.0, jnp.exp(m - m_new))
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        o = o * corr.swapaxes(1, 2) + jnp.einsum("bnqk,bknh->bqnh", p, v_blk)
        return m_new, l, o

    def fold_block(src, k_blk, v_blk, m, l, o):
        """Merge one visiting KV block into the local (m, l, o) —
        carried chunk-major ((n_chunks, B, ...) leading dim) when
        block_q is set, so the per-hop scan keeps only one chunk's score
        buffer live and no relayout happens inside the ring."""
        k_blk = k_blk.astype(jnp.float32)
        v_blk = v_blk.astype(jnp.float32)
        if rep != 1:
            k_blk = jnp.repeat(k_blk, rep, axis=2)
            v_blk = jnp.repeat(v_blk, rep, axis=2)
        if n_chunks == 1:
            return merge_chunk(src, 0, qf, k_blk, v_blk, m, l, o)

        def body(_, xs):
            qc, mc, lc, oc, off = xs
            return None, merge_chunk(src, off, qc, k_blk, v_blk,
                                     mc, lc, oc)

        _, out = lax.scan(body, None, (qx, m, l, o, offs))
        return out

    def fold(carry, t):
        # Permute at iteration START: n_dev-1 hops total, no dead final
        # transfer (the local block is folded outside the scan).
        k_blk, v_blk, m, l, o = carry
        k_blk, v_blk = jax.tree.map(
            lambda x: lax.ppermute(x, axis_name, perm), (k_blk, v_blk))
        m, l, o = fold_block((my - t) % n_dev, k_blk, v_blk, m, l, o)
        return (k_blk, v_blk, m, l, o), None

    if n_chunks == 1:
        m0 = jnp.full((B, nq, Sq, 1), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, nq, Sq, 1), jnp.float32)
        o0 = jnp.zeros((B, Sq, nq, hd), jnp.float32)
    else:
        m0 = jnp.full((n_chunks, B, nq, Cq, 1), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((n_chunks, B, nq, Cq, 1), jnp.float32)
        o0 = jnp.zeros((n_chunks, B, Cq, nq, hd), jnp.float32)
    m, l, o = fold_block(my, k, v, m0, l0, o0)          # t = 0: own block
    if n_dev > 1:
        (_, _, _, l, o), _ = lax.scan(fold, (k, v, m, l, o),
                                      jnp.arange(1, n_dev))
    if n_chunks > 1:  # chunk-major -> (B, ...) once, after the ring
        l = l.transpose(1, 2, 0, 3, 4).reshape(B, nq, Sq, 1)
        o = o.transpose(1, 0, 2, 3, 4).reshape(B, Sq, nq, hd)
    l = jnp.where(l == 0.0, 1.0, l)  # rows with no visible keys (unused)
    return (o / l.swapaxes(1, 2)).astype(q.dtype)


def zigzag_positions(axis_name: str, s_local: int) -> jax.Array:
    """Global token positions of this rank's zigzag chunk (stripe ``my``
    then stripe ``2D−1−my``) — what RoPE and the local causal mask see."""
    n_dev = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    w = s_local // 2
    ar = jnp.arange(w)
    return jnp.concatenate([my * w + ar, (2 * n_dev - 1 - my) * w + ar])


def _ring_zigzag(q, k, v, axis_name: str, *, scale: float,
                 block_q: int | None = None) -> jax.Array:
    """Causal ring attention over the zigzag/striped layout.

    Per remote hop: two FULLY-VISIBLE W×W products (module docstring) —
    no computed-then-masked scores; which second product runs is a
    ``lax.cond`` on src < my, so only the needed branch executes.  The
    local block (t = 0) is one position-masked product over the whole
    chunk.  Accumulators (m, l, o) span the full local S and products
    read/write their stripe's half via static slices."""
    n_dev = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, Sq, nq, hd = q.shape
    if Sq % 2:
        raise ValueError(f"zigzag needs an even local chunk, got {Sq}")
    W = Sq // 2
    nkv = k.shape[2]
    rep = nq // nkv
    if block_q is not None and block_q <= 0:
        raise ValueError(f"block_q={block_q} must be a positive divisor "
                         f"of S_local/2={W} (or None)")
    if block_q and block_q > W:
        raise ValueError(f"block_q={block_q} exceeds the zigzag stripe "
                         f"width S_local/2={W}")
    Cq = block_q if block_q and block_q < W else W
    if W % Cq:
        raise ValueError(f"block_q={block_q} must divide S_local/2={W}")
    qf = q.astype(jnp.float32)
    pos = zigzag_positions(axis_name, Sq)

    def merge(qc, k_blk, v_blk, m, l, o, qpos=None, kpos=None):
        """Online-softmax fold of one KV block into one q chunk's
        (m, l, o); positions given -> causal mask, None -> fully
        visible.  qc/o: (B, P, n, hd); m/l: (B, n, P, 1)."""
        s = _block_scores(qc, k_blk, scale)
        if qpos is not None:
            vis = kpos[None, :] <= qpos[:, None]
            s = jnp.where(vis[None, None], s, _NEG_INF)
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new)
        p = jnp.where(m_new <= _NEG_INF, 0.0, p)
        corr = jnp.where(m <= _NEG_INF, 0.0, jnp.exp(m - m_new))
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        o = o * corr.swapaxes(1, 2) + jnp.einsum("bnqk,bknh->bqnh", p,
                                                 v_blk)
        return m_new, l, o

    def product(qp, k_blk, v_blk, m, l, o, qpos=None, kpos=None):
        """``merge`` chunked over q rows by Cq (flash-style score-buffer
        bound).  qp: (B, P, nq, hd) with Cq | P."""
        P = qp.shape[1]
        if Cq >= P:
            return merge(qp, k_blk, v_blk, m, l, o, qpos, kpos)

        def body(carry, c):
            m, l, o = carry
            r0 = c * Cq
            qc = lax.dynamic_slice_in_dim(qp, r0, Cq, 1)
            mc = lax.dynamic_slice_in_dim(m, r0, Cq, 2)
            lc = lax.dynamic_slice_in_dim(l, r0, Cq, 2)
            oc = lax.dynamic_slice_in_dim(o, r0, Cq, 1)
            qpc = (lax.dynamic_slice_in_dim(qpos, r0, Cq, 0)
                   if qpos is not None else None)
            mc, lc, oc = merge(qc, k_blk, v_blk, mc, lc, oc, qpc, kpos)
            return (lax.dynamic_update_slice_in_dim(m, mc, r0, 2),
                    lax.dynamic_update_slice_in_dim(l, lc, r0, 2),
                    lax.dynamic_update_slice_in_dim(o, oc, r0, 1)), None

        (m, l, o), _ = lax.scan(body, (m, l, o), jnp.arange(P // Cq))
        return m, l, o

    def rep_kv(k_blk, v_blk):
        k_blk = k_blk.astype(jnp.float32)
        v_blk = v_blk.astype(jnp.float32)
        if rep != 1:
            k_blk = jnp.repeat(k_blk, rep, axis=2)
            v_blk = jnp.repeat(v_blk, rep, axis=2)
        return k_blk, v_blk

    m = jnp.full((B, nq, Sq, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((B, nq, Sq, 1), jnp.float32)
    o = jnp.zeros((B, Sq, nq, hd), jnp.float32)

    # t = 0: the local block, position-masked (covers both stripes' diag
    # sub-blocks and the always-visible q_late × k_early corner).
    kf, vf = rep_kv(k, v)
    m, l, o = product(qf, kf, vf, m, l, o, pos, pos)

    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def lower(mlo, half, vals):
        """Write (m, l, o) values into one stripe's half: 0 = early."""
        m, l, o = mlo
        mv, lv, ov = vals
        r0 = 0 if half == 0 else W
        return (lax.dynamic_update_slice_in_dim(m, mv, r0, 2),
                lax.dynamic_update_slice_in_dim(l, lv, r0, 2),
                lax.dynamic_update_slice_in_dim(o, ov, r0, 1))

    def lift(mlo, half):
        m, l, o = mlo
        r0 = 0 if half == 0 else W
        return (lax.dynamic_slice_in_dim(m, r0, W, 2),
                lax.dynamic_slice_in_dim(l, r0, W, 2),
                lax.dynamic_slice_in_dim(o, r0, W, 1))

    def fold(carry, t):
        k_blk, v_blk, m, l, o = carry
        k_blk, v_blk = jax.tree.map(
            lambda x: lax.ppermute(x, axis_name, perm), (k_blk, v_blk))
        src = (my - t) % n_dev
        kf, vf = rep_kv(k_blk, v_blk)
        ka, va = kf[:, :W], vf[:, :W]
        kb, vb = kf[:, W:], vf[:, W:]
        # product 1: q_late × k_early — visible for every src ≠ my.
        mlo = lower((m, l, o), 1,
                    product(qf[:, W:], ka, va, *lift((m, l, o), 1)))
        # product 2: src < my -> q_early × k_early; src > my ->
        # q_late × k_late.  Both fully visible; one branch executes.
        def early(mlo):
            return lower(mlo, 0,
                         product(qf[:, :W], ka, va, *lift(mlo, 0)))

        def late(mlo):
            return lower(mlo, 1,
                         product(qf[:, W:], kb, vb, *lift(mlo, 1)))

        m, l, o = lax.cond(src < my, early, late, mlo)
        return (k_blk, v_blk, m, l, o), None

    if n_dev > 1:
        (_, _, m, l, o), _ = lax.scan(fold, (k, v, m, l, o),
                                      jnp.arange(1, n_dev))
    l = jnp.where(l == 0.0, 1.0, l)
    return (o / l.swapaxes(1, 2)).astype(q.dtype)
