"""Collective-count verification from HLO — the build's upgrade over the
reference's by-eye trace counting.

The reference writes expected NCCL kernel counts in prose and checks profiler
traces manually ("+60 all_reduce +60 broadcast", reference ``README.md:16-20``).
Here the counts are *asserted in pytest*: lower a jitted function, count
collective ops in the StableHLO (pre-optimization — XLA fusion can merge or
reorder them later, SURVEY.md §7.3) and optionally in the compiled HLO.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Callable

import jax

# op-name patterns per collective, for both StableHLO and compiled HLO text.
# Compiled TPU HLO may emit async pairs (`all-reduce-start(...)` +
# `all-reduce-done(...)`); the sync opcode pattern `all-reduce\(` cannot match
# either async form (the char after the opcode stem is `-`, not `(`), so
# counting sync + `-start` sites — and never `-done` — counts each collective
# exactly once in both styles.
_PATTERNS = {
    "all_reduce": [r"stablehlo\.all_reduce",
                   r"\ball-reduce\(", r"\ball-reduce-start\("],
    "all_gather": [r"stablehlo\.all_gather",
                   r"\ball-gather\(", r"\ball-gather-start\("],
    "reduce_scatter": [r"stablehlo\.reduce_scatter",
                       r"\breduce-scatter\(", r"\breduce-scatter-start\("],
    "collective_permute": [r"stablehlo\.collective_permute",
                           r"\bcollective-permute\(",
                           r"\bcollective-permute-start\("],
    "all_to_all": [r"stablehlo\.all_to_all",
                   r"\ball-to-all\(", r"\ball-to-all-start\("],
}


def lowered_text(fn: Callable, *args, optimized: bool = False, **kwargs) -> str:
    """StableHLO (optimized=False) or post-XLA compiled HLO text of ``fn``."""
    lowered = jax.jit(fn).lower(*args, **kwargs)
    if optimized:
        return lowered.compile().as_text()
    return lowered.as_text()


def count_collectives(fn_or_text, *args, optimized: bool = False,
                      **kwargs) -> dict[str, int]:
    """Count collectives by kind.  Pass either a callable + example args, or
    an already-lowered HLO/StableHLO text."""
    if callable(fn_or_text):
        text = lowered_text(fn_or_text, *args, optimized=optimized, **kwargs)
    else:
        text = fn_or_text
    counts = {}
    for name, pats in _PATTERNS.items():
        counts[name] = sum(len(re.findall(p, text)) for p in pats)
    counts["total"] = sum(counts.values())
    return counts


# --------------------------------------------------------------- instances
#
# Per-instance parsing of *compiled* HLO: shape, payload bytes and replica
# groups of every collective — what the analysis subsystem lints against
# (``analysis.hlo_lint``).  count_collectives answers "how many"; this
# answers "of what, and across whom".

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

# "f32[16,16]{1,0}" / "bf16[8]" / "f32[]" — one array shape in HLO text.
_SHAPE_RE = re.compile(r"([a-z]+\d*(?:e\d+m\d+(?:fn)?)?)\[([\d,]*)\]")

# One collective instruction: "%name = <shape(s)> <opcode>(..." where the
# opcode is a sync collective or its async "-start" half ("-done" never
# matches: the char after the stem is "-", not "(" — same trick as
# _PATTERNS).
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*(?P<shape>\([^)]*\)|\S+)\s+"
    r"(?P<op>all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?P<start>-start)?\(")

_GROUPS_LITERAL_RE = re.compile(r"replica_groups=\{(\{[\d,{}\s]*\})\}")
# iota form: replica_groups=[4,2]<=[2,4]T(1,0) (transpose optional)
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")


def parse_shape(s: str) -> tuple[str, tuple[int, ...]] | None:
    """One HLO array shape string -> (dtype, dims), or None if not one."""
    m = _SHAPE_RE.match(s)
    if not m:
        return None
    dt, dims = m.group(1), m.group(2)
    return dt, tuple(int(d) for d in dims.split(",")) if dims else ()


def parse_replica_groups(line: str) -> tuple[tuple[int, ...], ...] | None:
    """The replica groups of one HLO instruction line, as a tuple of
    device-id groups.  Handles both the literal ``{{0,1},{2,3}}`` form and
    the iota ``[G,S]<=[dims]T(perm)`` form (reshape-transpose of
    ``arange(n)``).  None when the line carries no parseable groups."""
    m = _GROUPS_LITERAL_RE.search(line)
    if m:
        groups = []
        for g in re.findall(r"\{([\d,\s]*)\}", m.group(1)):
            ids = tuple(int(x) for x in g.replace(" ", "").split(",") if x)
            if ids:
                groups.append(ids)
        return tuple(groups) if groups else None
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        n_groups, group_size = int(m.group(1)), int(m.group(2))
        dims = [int(d) for d in m.group(3).split(",")]
        seq = list(range(math.prod(dims)))
        if m.group(4):  # reshape to dims, transpose, then regroup
            perm = [int(p) for p in m.group(4).split(",")]
            import numpy as np
            arr = np.arange(math.prod(dims)).reshape(dims).transpose(perm)
            seq = list(arr.reshape(-1))
        return tuple(
            tuple(int(i) for i in seq[g * group_size:(g + 1) * group_size])
            for g in range(n_groups))
    return None


@dataclass(frozen=True)
class CollectiveInstance:
    """One collective instruction parsed out of compiled HLO text."""
    kind: str                                   # "all_reduce", ... (as in
    #                                             count_collectives keys)
    shapes: tuple[tuple[int, ...], ...] = ()    # output array dims
    dtypes: tuple[str, ...] = ()
    bytes: int = 0                              # summed output payload
    replica_groups: tuple[tuple[int, ...], ...] | None = None
    is_async_start: bool = False
    line: str = field(default="", compare=False)
    # instruction name ("all-reduce.1") — profiler trace events carry the
    # same name, so this is the join key of telemetry.ledger
    name: str = ""


def collective_instances(text: str) -> list[CollectiveInstance]:
    """Every collective in compiled HLO text, with shapes + replica groups.

    Async pairs are counted once (the ``-start`` op carries the info; the
    ``-done`` op never matches).  Works on post-XLA ``compile().as_text()``
    output; StableHLO callers should keep using ``count_collectives``."""
    out = []
    for raw in text.splitlines():
        m = _INSTR_RE.match(raw)
        if not m:
            continue
        shapes, dtypes, nbytes = [], [], 0
        for sm in _SHAPE_RE.finditer(m.group("shape")):
            dt = sm.group(1)
            dims = tuple(int(d) for d in sm.group(2).split(",")) \
                if sm.group(2) else ()
            shapes.append(dims)
            dtypes.append(dt)
            nbytes += math.prod(dims) * _DTYPE_BYTES.get(dt, 4)
        out.append(CollectiveInstance(
            kind=m.group("op").replace("-", "_"),
            shapes=tuple(shapes), dtypes=tuple(dtypes), bytes=nbytes,
            replica_groups=parse_replica_groups(raw),
            is_async_start=bool(m.group("start")), line=raw.strip(),
            name=m.group("name")))
    return out
