"""Collective-count verification from HLO — the build's upgrade over the
reference's by-eye trace counting.

The reference writes expected NCCL kernel counts in prose and checks profiler
traces manually ("+60 all_reduce +60 broadcast", reference ``README.md:16-20``).
Here the counts are *asserted in pytest*: lower a jitted function, count
collective ops in the StableHLO (pre-optimization — XLA fusion can merge or
reorder them later, SURVEY.md §7.3) and optionally in the compiled HLO.
"""

from __future__ import annotations

import re
from typing import Callable

import jax

# op-name patterns per collective, for both StableHLO and compiled HLO text.
# Compiled TPU HLO may emit async pairs (`all-reduce-start(...)` +
# `all-reduce-done(...)`); the sync opcode pattern `all-reduce\(` cannot match
# either async form (the char after the opcode stem is `-`, not `(`), so
# counting sync + `-start` sites — and never `-done` — counts each collective
# exactly once in both styles.
_PATTERNS = {
    "all_reduce": [r"stablehlo\.all_reduce",
                   r"\ball-reduce\(", r"\ball-reduce-start\("],
    "all_gather": [r"stablehlo\.all_gather",
                   r"\ball-gather\(", r"\ball-gather-start\("],
    "reduce_scatter": [r"stablehlo\.reduce_scatter",
                       r"\breduce-scatter\(", r"\breduce-scatter-start\("],
    "collective_permute": [r"stablehlo\.collective_permute",
                           r"\bcollective-permute\(",
                           r"\bcollective-permute-start\("],
    "all_to_all": [r"stablehlo\.all_to_all",
                   r"\ball-to-all\(", r"\ball-to-all-start\("],
}


def lowered_text(fn: Callable, *args, optimized: bool = False, **kwargs) -> str:
    """StableHLO (optimized=False) or post-XLA compiled HLO text of ``fn``."""
    lowered = jax.jit(fn).lower(*args, **kwargs)
    if optimized:
        return lowered.compile().as_text()
    return lowered.as_text()


def count_collectives(fn_or_text, *args, optimized: bool = False,
                      **kwargs) -> dict[str, int]:
    """Count collectives by kind.  Pass either a callable + example args, or
    an already-lowered HLO/StableHLO text."""
    if callable(fn_or_text):
        text = lowered_text(fn_or_text, *args, optimized=optimized, **kwargs)
    else:
        text = fn_or_text
    counts = {}
    for name, pats in _PATTERNS.items():
        counts[name] = sum(len(re.findall(p, text)) for p in pats)
    counts["total"] = sum(counts.values())
    return counts
