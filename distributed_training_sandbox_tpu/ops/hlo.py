"""Collective-count verification from HLO — the build's upgrade over the
reference's by-eye trace counting.

The reference writes expected NCCL kernel counts in prose and checks profiler
traces manually ("+60 all_reduce +60 broadcast", reference ``README.md:16-20``).
Here the counts are *asserted in pytest*: lower a jitted function, count
collective ops in the StableHLO (pre-optimization — XLA fusion can merge or
reorder them later, SURVEY.md §7.3) and optionally in the compiled HLO.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Callable

import jax

# op-name patterns per collective, for both StableHLO and compiled HLO text.
# Compiled TPU HLO may emit async pairs (`all-reduce-start(...)` +
# `all-reduce-done(...)`); the sync opcode pattern `all-reduce\(` cannot match
# either async form (the char after the opcode stem is `-`, not `(`), so
# counting sync + `-start` sites — and never `-done` — counts each collective
# exactly once in both styles.
_PATTERNS = {
    "all_reduce": [r"stablehlo\.all_reduce",
                   r"\ball-reduce\(", r"\ball-reduce-start\("],
    "all_gather": [r"stablehlo\.all_gather",
                   r"\ball-gather\(", r"\ball-gather-start\("],
    "reduce_scatter": [r"stablehlo\.reduce_scatter",
                       r"\breduce-scatter\(", r"\breduce-scatter-start\("],
    "collective_permute": [r"stablehlo\.collective_permute",
                           r"\bcollective-permute\(",
                           r"\bcollective-permute-start\("],
    "all_to_all": [r"stablehlo\.all_to_all",
                   r"\ball-to-all\(", r"\ball-to-all-start\("],
}


def lowered_text(fn: Callable, *args, optimized: bool = False, **kwargs) -> str:
    """StableHLO (optimized=False) or post-XLA compiled HLO text of ``fn``."""
    lowered = jax.jit(fn).lower(*args, **kwargs)
    if optimized:
        return lowered.compile().as_text()
    return lowered.as_text()


def count_collectives(fn_or_text, *args, optimized: bool = False,
                      **kwargs) -> dict[str, int]:
    """Count collectives by kind.  Pass either a callable + example args, or
    an already-lowered HLO/StableHLO text."""
    if callable(fn_or_text):
        text = lowered_text(fn_or_text, *args, optimized=optimized, **kwargs)
    else:
        text = fn_or_text
    counts = {}
    for name, pats in _PATTERNS.items():
        counts[name] = sum(len(re.findall(p, text)) for p in pats)
    counts["total"] = sum(counts.values())
    return counts


# --------------------------------------------------------------- instances
#
# Per-instance parsing of *compiled* HLO: shape, payload bytes and replica
# groups of every collective — what the analysis subsystem lints against
# (``analysis.hlo_lint``).  count_collectives answers "how many"; this
# answers "of what, and across whom".

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

# "f32[16,16]{1,0}" / "bf16[8]" / "f32[]" — one array shape in HLO text.
_SHAPE_RE = re.compile(r"([a-z]+\d*(?:e\d+m\d+(?:fn)?)?)\[([\d,]*)\]")

# One collective instruction: "%name = <shape(s)> <opcode>(..." where the
# opcode is a sync collective or its async "-start" half ("-done" never
# matches: the char after the stem is "-", not "(" — same trick as
# _PATTERNS).
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*(?P<shape>\([^)]*\)|\S+)\s+"
    r"(?P<op>all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?P<start>-start)?\(")

_GROUPS_LITERAL_RE = re.compile(r"replica_groups=\{(\{[\d,{}\s]*\})\}")
# iota form: replica_groups=[4,2]<=[2,4]T(1,0) (transpose optional)
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")


def parse_shape(s: str) -> tuple[str, tuple[int, ...]] | None:
    """One HLO array shape string -> (dtype, dims), or None if not one."""
    m = _SHAPE_RE.match(s)
    if not m:
        return None
    dt, dims = m.group(1), m.group(2)
    return dt, tuple(int(d) for d in dims.split(",")) if dims else ()


def parse_replica_groups(line: str) -> tuple[tuple[int, ...], ...] | None:
    """The replica groups of one HLO instruction line, as a tuple of
    device-id groups.  Handles both the literal ``{{0,1},{2,3}}`` form and
    the iota ``[G,S]<=[dims]T(perm)`` form (reshape-transpose of
    ``arange(n)``).  None when the line carries no parseable groups."""
    m = _GROUPS_LITERAL_RE.search(line)
    if m:
        groups = []
        for g in re.findall(r"\{([\d,\s]*)\}", m.group(1)):
            ids = tuple(int(x) for x in g.replace(" ", "").split(",") if x)
            if ids:
                groups.append(ids)
        return tuple(groups) if groups else None
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        n_groups, group_size = int(m.group(1)), int(m.group(2))
        dims = [int(d) for d in m.group(3).split(",")]
        seq = list(range(math.prod(dims)))
        if m.group(4):  # reshape to dims, transpose, then regroup
            perm = [int(p) for p in m.group(4).split(",")]
            import numpy as np
            arr = np.arange(math.prod(dims)).reshape(dims).transpose(perm)
            seq = list(arr.reshape(-1))
        return tuple(
            tuple(int(i) for i in seq[g * group_size:(g + 1) * group_size])
            for g in range(n_groups))
    return None


# --------------------------------------------------------------- shardings
#
# Entry-param/output sharding annotations of *compiled* HLO: what the
# rule-based analyzer (``analysis.rules``) lints against.  A compiled
# entry parameter line looks like
#
#   %param.1 = f32[2,16,4]{2,1,0} parameter(1),
#       sharding={devices=[1,1,2,4]<=[4,2]T(1,0) last_tile_dim_replicate},
#       metadata={op_name="p['layers']['wq']"}
#
# and the V1 literal form spells the device list out:
#   sharding={devices=[2,4]0,1,2,3,4,5,6,7}
#
# The analyzer compares *tile factor per dimension* (how many ways each
# dim is split), which both forms carry in the leading dims vector —
# device order is the replica-group lint's job, not this one's.

# the {...} payload of one sharding= attribute
_SHARDING_ATTR_RE = re.compile(r"sharding=\{([^{}]*(?:\{[^{}]*\}[^{}]*)*)\}")
# V1/V2 tile dims: devices=[2,4]... — the dims vector is common to both
_SHARDING_DEVICES_RE = re.compile(r"devices=\[([\d,]+)\]")
_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')
_PARAM_NO_RE = re.compile(r"parameter\((\d+)\)")


@dataclass(frozen=True)
class ShardingAnnotation:
    """One parsed ``sharding={...}`` attribute (array, not tuple)."""
    raw: str
    replicated: bool = False
    maximal: bool = False                  # {maximal device=k}
    tile_dims: tuple[int, ...] = ()        # tile factor per array dim
    last_tile_dim_replicate: bool = False

    def tiles(self, ndim: int) -> tuple[int, ...]:
        """Tile factor per array dimension, normalized to ``ndim``
        entries: replicated/maximal -> all 1s; a trailing
        last_tile_dim_replicate (or subgroup manual) dim is dropped."""
        if self.replicated or self.maximal:
            return (1,) * ndim
        dims = self.tile_dims
        if len(dims) > ndim:          # replicate/manual subgroup tail
            dims = dims[:ndim]
        return tuple(dims) + (1,) * (ndim - len(dims))


def parse_sharding(text: str) -> ShardingAnnotation | None:
    """Parse the first ``sharding={...}`` attribute on one HLO line (or a
    bare ``{...}`` payload).  Returns None when the line carries none.
    Tuple shardings (``{{...}, {...}}``) should be split by the caller
    (see :func:`entry_output_shardings`)."""
    m = _SHARDING_ATTR_RE.search(text)
    payload = m.group(1) if m else None
    if payload is None:
        if text.lstrip().startswith("{") or "devices=" in text \
                or "replicated" in text or "maximal" in text:
            payload = text.strip().strip("{}")
        else:
            return None
    payload = payload.strip()
    if payload.startswith("replicated"):
        return ShardingAnnotation(raw=payload, replicated=True)
    if payload.startswith("maximal"):
        return ShardingAnnotation(raw=payload, maximal=True)
    dm = _SHARDING_DEVICES_RE.search(payload)
    if not dm:
        return None
    dims = tuple(int(d) for d in dm.group(1).split(","))
    return ShardingAnnotation(
        raw=payload, tile_dims=dims,
        last_tile_dim_replicate="last_tile_dim_replicate" in payload)


@dataclass(frozen=True)
class EntryParamSharding:
    """One entry-computation parameter of a compiled module."""
    index: int
    dtype: str = ""
    dims: tuple[int, ...] = ()             # LOCAL (per-shard) dims in SPMD
    sharding: ShardingAnnotation | None = None
    op_name: str = ""                      # jax keypath, e.g. "p['embed']"
    line: str = field(default="", compare=False)


def _entry_lines(text: str):
    """The instruction lines of the ENTRY computation only — nested
    computations (scan bodies, fusions) carry parameters too."""
    inside = False
    for raw in text.splitlines():
        if raw.startswith("ENTRY"):
            inside = True
            continue
        if inside:
            if raw.strip() == "}":
                return
            yield raw


def entry_parameter_shardings(text: str) -> list[EntryParamSharding]:
    """Every ``parameter(i)`` of the ENTRY computation with its parsed
    sharding annotation (None when the compiler printed none), sorted by
    parameter index — which is the flatten order of the jitted callable's
    arguments, so rule-derived specs join positionally."""
    out = []
    for raw in _entry_lines(text):
        if "parameter(" not in raw:
            continue
        pm = _PARAM_NO_RE.search(raw)
        if not pm:
            continue
        shape = parse_shape(raw.split("=", 1)[1].strip()) \
            if "=" in raw else None
        nm = _OP_NAME_RE.search(raw)
        out.append(EntryParamSharding(
            index=int(pm.group(1)),
            dtype=shape[0] if shape else "",
            dims=shape[1] if shape else (),
            sharding=parse_sharding(raw),
            op_name=nm.group(1) if nm else "",
            line=raw.strip()))
    return sorted(out, key=lambda p: p.index)


def entry_output_shardings(text: str) -> list[ShardingAnnotation | None]:
    """The ROOT tuple's per-element sharding annotations (flatten order
    of the jitted callable's outputs), or ``[]`` when the compiled entry
    root carries no sharding attribute — output lint is best-effort."""
    for raw in _entry_lines(text):
        if not raw.lstrip().startswith("ROOT"):
            continue
        m = _SHARDING_ATTR_RE.search(raw)
        if not m:
            return []
        payload = m.group(1)
        parts = re.findall(r"\{[^{}]*\}", payload)
        if not parts:                      # single-array root
            ann = parse_sharding(raw)
            return [ann] if ann else []
        return [parse_sharding(p) for p in parts]
    return []


@dataclass(frozen=True)
class CollectiveInstance:
    """One collective instruction parsed out of compiled HLO text."""
    kind: str                                   # "all_reduce", ... (as in
    #                                             count_collectives keys)
    shapes: tuple[tuple[int, ...], ...] = ()    # output array dims
    dtypes: tuple[str, ...] = ()
    bytes: int = 0                              # summed output payload
    replica_groups: tuple[tuple[int, ...], ...] | None = None
    is_async_start: bool = False
    line: str = field(default="", compare=False)
    # instruction name ("all-reduce.1") — profiler trace events carry the
    # same name, so this is the join key of telemetry.ledger
    name: str = ""


def collective_instances(text: str) -> list[CollectiveInstance]:
    """Every collective in compiled HLO text, with shapes + replica groups.

    Async pairs are counted once (the ``-start`` op carries the info; the
    ``-done`` op never matches).  Works on post-XLA ``compile().as_text()``
    output; StableHLO callers should keep using ``count_collectives``."""
    out = []
    for raw in text.splitlines():
        m = _INSTR_RE.match(raw)
        if not m:
            continue
        shapes, dtypes, nbytes = [], [], 0
        for sm in _SHAPE_RE.finditer(m.group("shape")):
            dt = sm.group(1)
            dims = tuple(int(d) for d in sm.group(2).split(",")) \
                if sm.group(2) else ()
            shapes.append(dims)
            dtypes.append(dt)
            nbytes += math.prod(dims) * _DTYPE_BYTES.get(dt, 4)
        out.append(CollectiveInstance(
            kind=m.group("op").replace("-", "_"),
            shapes=tuple(shapes), dtypes=tuple(dtypes), bytes=nbytes,
            replica_groups=parse_replica_groups(raw),
            is_async_start=bool(m.group("start")), line=raw.strip(),
            name=m.group("name")))
    return out
