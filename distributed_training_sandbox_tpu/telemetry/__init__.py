"""Unified run telemetry — the machine-readable observability layer the
reference's per-script print lines never had.

Every training script emits the same three artifacts under
``<results_dir>/<run_id>/``:

  * ``manifest.json``  — immutable startup facts (:class:`RunManifest`):
    strategy, full ``TrainConfig``, mesh shape, device kind/count,
    jax/jaxlib versions, git sha, compile-time HLO collective counts;
  * ``steps.jsonl``    — one event per optimizer step under the shared
    schema (:mod:`.schema`), fed by ``PerformanceTracker`` metrics;
  * ``summary.json``   — end-of-run aggregates plus, when profiling was
    on, the ``trace_analysis.split_from_trace`` comm/compute split and
    the trace directory.

``scripts/report.py`` reads these back for the cross-run side-by-side
table and regression deltas — the ICI half of the NCCL-vs-ICI
comparison in BASELINE.md.
"""

from .schema import STEP_SCHEMA_VERSION, step_event  # noqa: F401
from .manifest import RunManifest  # noqa: F401
from .writer import MetricsWriter  # noqa: F401
from .run import TelemetryRun  # noqa: F401
from .report import (  # noqa: F401
    discover_runs,
    load_baseline_rows,
    render_table,
    check_regressions,
)
