"""Unified run telemetry — the machine-readable observability layer the
reference's per-script print lines never had.

Every training script emits the same three artifacts under
``<results_dir>/<run_id>/``:

  * ``manifest.json``  — immutable startup facts (:class:`RunManifest`):
    strategy, full ``TrainConfig``, mesh shape, device kind/count,
    jax/jaxlib versions, git sha, compile-time HLO collective counts;
  * ``steps.jsonl``    — one event per optimizer step under the shared
    schema (:mod:`.schema`), fed by ``PerformanceTracker`` metrics;
  * ``summary.json``   — end-of-run aggregates plus, when profiling was
    on, the ``trace_analysis.split_from_trace`` comm/compute split of
    the owned profiler session and the trace directory;
  * ``spans.jsonl``    — host-side phase spans (:mod:`.spans`): prefetch
    waits, pump sync barriers, checkpoint saves, serving bursts —
    merged with the device trace by ``scripts/export_timeline.py`` and
    across ranks by ``scripts/fleet_timeline.py`` (each stream writes a
    ``clock_anchor.json`` epoch↔perf_counter sidecar for the merge);
  * ``collectives.json`` — the :mod:`.ledger` CollectiveLedger: per
    compiled collective instruction, measured duration + payload bytes
    + achieved algo/bus GB/s, joined against the strategy's
    CollectiveContract (the measured verdict also lands in
    ``manifest.json`` beside the static one);
  * ``memory.json``    — the :mod:`.memledger` MemoryLedger: the compiled
    step's ``memory_analysis()`` waterline attributed to categories
    (params / opt-state / saved activations / collective scratch) plus
    the phase-spanned allocator timeline; its MemoryVerdict — measured
    peak vs planner prediction — is the third manifest mark.

``scripts/report.py`` reads these back for the cross-run side-by-side
table and regression deltas — the ICI half of the NCCL-vs-ICI
comparison in BASELINE.md.  ``scripts/runs.py`` indexes whole results
trees into a queryable sqlite registry (and folds ledger aggregates
into the autotuner's ``cost_model.json``), and :mod:`.metrics` adds the
live side: a :class:`MetricsRegistry` scrapeable over HTTP while the
run is still going.
"""

from .schema import (  # noqa: F401
    SPAN_SCHEMA_VERSION,
    STEP_SCHEMA_VERSION,
    span_event,
    step_event,
)
from .manifest import RunManifest  # noqa: F401
from .writer import MetricsWriter  # noqa: F401
from .spans import (  # noqa: F401
    SpanStream,
    maybe_span,
    read_clock_anchor,
    read_spans,
)
from .metrics import (  # noqa: F401
    MetricsRegistry,
    MetricsServer,
    maybe_inc,
    maybe_observe,
    maybe_set,
)
from .ledger import (  # noqa: F401
    CollectiveLedger,
    LedgerEntry,
    build_ledger,
    check_bandwidth_regressions,
    join_contract,
    ledger_from_trace,
    load_ledger_dict,
)
from .memledger import (  # noqa: F401
    MEMORY_FILENAME,
    MemoryLedger,
    MemorySampler,
    build_memory_ledger,
    check_memory_regressions,
    get_sampler,
    join_prediction,
    load_memory_dict,
    memory_aggregates,
    phase_for_span,
)
from .run import TelemetryRun  # noqa: F401
from .report import (  # noqa: F401
    discover_runs,
    load_baseline_rows,
    render_table,
    check_regressions,
)
