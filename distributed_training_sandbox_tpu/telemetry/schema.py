"""The shared per-step event schema.

One JSONL line per optimizer step, identical across every strategy
script, so ``scripts/report.py`` can compare runs without per-script
parsers (the ``*_results/`` dirs each grew a bespoke schema; this is
the one they converge on going forward).

Field semantics:
  ``schema``            int, :data:`STEP_SCHEMA_VERSION`
  ``step``              0-based optimizer-step index within the run
  ``loss``              scalar loss for this step (None while unknown)
  ``tokens``            tokens consumed by this step (global batch)
  ``step_time_s``       wall-clock of this step, host-side
  ``tokens_per_second`` cumulative post-warmup rate (tracker window)
  ``tflops_per_device`` analytic-FLOPs rate per device (None w/o model)
  ``peak_memory_gb``    allocator peak on device 0 (None on CPU sim)

Serving events (``serving.engine`` — one line per prefill completion or
decode burst) ride the same schema with the optional fields below;
``step`` counts engine events, ``tokens`` are prompt tokens (prefill)
or emitted tokens (decode burst), ``step_time_s`` the chunk / per-step
burst time:
  ``phase``            "prefill" | "decode"
  ``active``           mean active slots over the burst
  ``admitted``         requests admitted so far
  ``completed``        requests retired so far
  ``kv_pages_in_use``  pool pages currently granted
  ``pool_util``        granted / usable pages (0..1)
  ``ttft_ms``          this request's time-to-first-token (prefill)
  ``completed_requests`` per-request {rid, ttft_ms, per_token_ms,
                       tokens, trace_id} retired at this burst's sync
                       point
  ``replica``          fleet replica index that emitted the event
                       (absent on single-engine runs)
  ``request_id``       engine-local request id for per-request events
                       (prefill completions); optional, additive
  ``trace_id``         distributed trace id minted at Router.submit —
                       stable across failover replay, joins an event to
                       its request swimlane; optional, additive
  ``rank``             emitting process rank (``DTS_PROCESS_ID``);
                       optional, stamped on multi-process runs

The ``request_id`` / ``trace_id`` / ``rank`` fields are additive and
optional — the schema version is unchanged and pre-existing reports
parse events that carry them without modification.
"""

from __future__ import annotations

from typing import Any

STEP_SCHEMA_VERSION = 1

# ordered for stable JSONL key order; value = required at write time
STEP_FIELDS = {
    "schema": True,
    "step": True,
    "loss": False,
    "tokens": False,
    "step_time_s": False,
    "tokens_per_second": False,
    "tflops_per_device": False,
    "peak_memory_gb": False,
    # serving-runtime extras (absent on training events)
    "replica": False,
    "phase": False,
    "active": False,
    "admitted": False,
    "completed": False,
    "kv_pages_in_use": False,
    "pool_util": False,
    "ttft_ms": False,
    "completed_requests": False,
    # distributed-tracing extras (optional, schema version unchanged)
    "request_id": False,
    "trace_id": False,
    "rank": False,
}


def step_event(step: int, *, loss: float | None = None,
               tokens: int | None = None,
               step_time_s: float | None = None,
               tracker_metrics: dict | None = None,
               **extra: Any) -> dict:
    """Build one schema-versioned step event.  ``tracker_metrics`` is the
    dict returned by ``PerformanceTracker.step``/``.metrics`` — the rate
    and memory fields are lifted from it when present."""
    tm = tracker_metrics or {}
    ev: dict[str, Any] = {
        "schema": STEP_SCHEMA_VERSION,
        "step": int(step),
        "loss": None if loss is None else float(loss),
        "tokens": None if tokens is None else int(tokens),
        "step_time_s": (float(step_time_s) if step_time_s is not None
                        else tm.get("last_step_time_s")),
        "tokens_per_second": tm.get("tokens_per_second"),
        "tflops_per_device": tm.get("tflops_per_device"),
        "peak_memory_gb": tm.get("peak_memory_gb"),
    }
    for k, v in extra.items():
        ev.setdefault(k, v)
    return ev


# ------------------------------------------------------------- host spans
#
# One JSONL line per host-side phase span (telemetry.spans.SpanStream):
# where the host spent time *between* step events — prefetch waits, pump
# sync barriers, checkpoint saves, serving bursts.  ``ts_us`` is
# unix-epoch microseconds of span start; ``dur_us`` the span length.

SPAN_SCHEMA_VERSION = 1

SPAN_FIELDS = {
    "schema": True,
    "name": True,      # "pump/sync_every", "prefetch/wait", ...
    "cat": False,      # coarse category: "pump" | "prefetch" | ...
    "ts_us": True,
    "dur_us": True,
}


def span_event(name: str, *, ts_us: float, dur_us: float,
               cat: str | None = None, **attrs: Any) -> dict:
    ev: dict[str, Any] = {
        "schema": SPAN_SCHEMA_VERSION,
        "name": str(name),
        "cat": cat or str(name).split("/", 1)[0],
        "ts_us": float(ts_us),
        "dur_us": float(dur_us),
    }
    for k, v in attrs.items():
        if v is not None:
            ev.setdefault(k, v)
    return ev


def validate_span(ev: dict) -> list[str]:
    problems = []
    for field, required in SPAN_FIELDS.items():
        if required and field not in ev:
            problems.append(f"missing required span field {field!r}")
    for field in ("ts_us", "dur_us"):
        v = ev.get(field)
        if v is not None and not isinstance(v, (int, float)):
            problems.append(f"{field} must be numeric, got {v!r}")
    return problems


def validate_step(ev: dict) -> list[str]:
    """Schema-check one parsed event; returns a list of problems (empty
    when valid).  Used by tests and by ``report.py --strict``."""
    problems = []
    for field, required in STEP_FIELDS.items():
        if required and field not in ev:
            problems.append(f"missing required field {field!r}")
    if ev.get("schema") not in (None, STEP_SCHEMA_VERSION):
        problems.append(f"unknown schema version {ev.get('schema')!r}")
    for field in ("loss", "step_time_s", "tokens_per_second",
                  "tflops_per_device", "peak_memory_gb", "active",
                  "admitted", "completed", "kv_pages_in_use",
                  "pool_util", "ttft_ms"):
        v = ev.get(field)
        if v is not None and not isinstance(v, (int, float)):
            problems.append(f"{field} must be numeric or null, got {v!r}")
    return problems
