"""Host-side phase spans: the fourth telemetry artifact.

``steps.jsonl`` says what each optimizer step cost; it cannot say *where
the host spent the gaps* — blocked on the prefetch queue, barriered at a
pump sync point, throttled on in-flight backpressure, inside an Orbax
checkpoint write, or driving a serving prefill/decode burst.  Each of
those sites records a :func:`maybe_span` here, appended to
``spans.jsonl`` in the run dir, and ``scripts/export_timeline.py`` merges
them with the device trace into one chrome-trace/Perfetto timeline.

Schema (one JSON line per span, ``schema.span_event``):

    {"schema": 1, "name": "pump/sync_every", "cat": "pump",
     "ts_us": <unix-epoch µs of span start>, "dur_us": <float>, ...attrs}

Timestamps are unix-epoch microseconds derived from a
``perf_counter``-anchored clock captured at stream construction, so
spans from different threads (the prefetcher's producer records from its
own thread) share one monotonic timebase.  The anchor is a
bounded-error midpoint capture — ``perf_counter`` is read immediately
before *and* after ``time.time()``, the anchor sits at the midpoint and
half the window is the error bound — and is persisted to a
``clock_anchor.json`` sidecar (lazily, alongside the first span, so
span-free runs produce no extra files).  ``scripts/fleet_timeline.py``
uses the sidecars to align per-rank streams from one launch group on a
shared epoch timebase.  Every span is additionally stamped with the
emitting ``rank`` (``DTS_PROCESS_ID``) and ``pid`` so merged streams
stay attributable.  The stream is thread-safe and crash-tolerant:
appends are flushed every :data:`FLUSH_EVERY` events and on
``close()``, which ``TelemetryRun.finalize`` reaches on every path.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time

from .schema import span_event

FLUSH_EVERY = 32


class SpanStream:
    """Append-only ``spans.jsonl`` writer with a shared time anchor."""

    FILENAME = "spans.jsonl"

    ANCHOR_FILENAME = "clock_anchor.json"

    def __init__(self, run_dir: str, flush_every: int = FLUSH_EVERY):
        self.path = os.path.join(run_dir, self.FILENAME)
        self.anchor_path = os.path.join(run_dir, self.ANCHOR_FILENAME)
        # one anchor pair: unix epoch + the perf_counter reading at the
        # same instant; every span timestamp is
        # epoch + (perf_now - perf_anchor), monotonic across threads.
        # perf_counter is sampled before AND after time.time() so the
        # anchor can sit at the midpoint with a known error bound of
        # half the capture window — cross-rank merges need the bound.
        perf_before = time.perf_counter()
        epoch = time.time()
        perf_after = time.perf_counter()
        self._epoch_us = epoch * 1e6
        self._perf_anchor = (perf_before + perf_after) / 2.0
        self.anchor_error_us = (perf_after - perf_before) / 2.0 * 1e6
        self.rank = int(os.environ.get("DTS_PROCESS_ID", "0") or 0)
        self.pid = os.getpid()
        self._anchor_written = False
        # optional memledger.MemorySampler: when wired (TelemetryRun.start
        # does), every span append also folds one allocator read into the
        # span's memory phase — the "phase-spanned" half of memory.json
        self.sampler = None
        self._lock = threading.Lock()
        self._f = None
        self._unflushed = 0
        self.flush_every = max(int(flush_every), 1)
        self.spans_written = 0
        self._closed = False

    def _now_us(self) -> float:
        return self._epoch_us + (time.perf_counter()
                                 - self._perf_anchor) * 1e6

    def record(self, name: str, *, start_perf: float, end_perf: float,
               cat: str | None = None, **attrs) -> None:
        """File one completed span given its ``perf_counter`` bounds —
        the form for call sites that already stopwatch themselves (the
        serving engine's burst timers)."""
        ts = self._epoch_us + (start_perf - self._perf_anchor) * 1e6
        self._append(span_event(name, ts_us=ts,
                                dur_us=(end_perf - start_perf) * 1e6,
                                cat=cat, **attrs))

    @contextlib.contextmanager
    def span(self, name: str, cat: str | None = None, **attrs):
        """Context-manager form: times the body, files on exit (also on
        exception — a crashed wait still shows in the timeline)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, start_perf=t0,
                        end_perf=time.perf_counter(), cat=cat, **attrs)

    # ---- file plumbing --------------------------------------------------
    def _write_anchor(self) -> None:
        """Persist the clock-anchor sidecar (caller holds the lock).
        Written lazily with the first span so span-free runs keep their
        exact artifact set."""
        anchor = {
            "schema": 1,
            "epoch_us": self._epoch_us,
            "perf_anchor_s": self._perf_anchor,
            "anchor_error_us": self.anchor_error_us,
            "rank": self.rank,
            "pid": self.pid,
        }
        tmp = self.anchor_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(anchor, f, indent=2)
        os.replace(tmp, self.anchor_path)
        self._anchor_written = True

    def _append(self, ev: dict) -> None:
        ev.setdefault("rank", self.rank)
        ev.setdefault("pid", self.pid)
        if self.sampler is not None:
            # outside the file lock: the sampler has its own, and a
            # device round-trip under the append lock would serialize
            # producer threads
            from .memledger import phase_for_span
            ph = phase_for_span(ev.get("name", ""), ev.get("cat"))
            if ph:
                try:
                    self.sampler.sample(phase=ph)
                except Exception:
                    pass
        with self._lock:
            if self._closed:
                return
            if self._f is None:
                os.makedirs(os.path.dirname(self.path), exist_ok=True)
                self._f = open(self.path, "a")
            if not self._anchor_written:
                self._write_anchor()
            self._f.write(json.dumps(ev, default=str) + "\n")
            self.spans_written += 1
            self._unflushed += 1
            if self._unflushed >= self.flush_every:
                self._f.flush()
                self._unflushed = 0

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._f is not None:
                self._f.flush()
                self._f.close()
                self._f = None


@contextlib.contextmanager
def maybe_span(stream, name: str, cat: str | None = None, **attrs):
    """``stream.span(...)`` when a stream is wired, no-op when ``stream``
    is None — the guard every runtime call site uses so spans never
    impose a telemetry dependency."""
    if stream is None:
        yield
        return
    # forwarder: the caller's literal passes through (lint checks THEM)
    with stream.span(name, cat=cat, **attrs):   # span-ok
        yield


def read_clock_anchor(run_dir: str) -> dict | None:
    """Parse ``<run_dir>/clock_anchor.json`` (missing -> None)."""
    path = os.path.join(run_dir, SpanStream.ANCHOR_FILENAME)
    if not os.path.isfile(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def read_spans(run_dir: str) -> list[dict]:
    """Parse ``<run_dir>/spans.jsonl`` (missing file -> empty list)."""
    path = os.path.join(run_dir, SpanStream.FILENAME)
    out = []
    if os.path.isfile(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue
    return out
