"""Host-side phase spans: the fourth telemetry artifact.

``steps.jsonl`` says what each optimizer step cost; it cannot say *where
the host spent the gaps* — blocked on the prefetch queue, barriered at a
pump sync point, throttled on in-flight backpressure, inside an Orbax
checkpoint write, or driving a serving prefill/decode burst.  Each of
those sites records a :func:`maybe_span` here, appended to
``spans.jsonl`` in the run dir, and ``scripts/export_timeline.py`` merges
them with the device trace into one chrome-trace/Perfetto timeline.

Schema (one JSON line per span, ``schema.span_event``):

    {"schema": 1, "name": "pump/sync_every", "cat": "pump",
     "ts_us": <unix-epoch µs of span start>, "dur_us": <float>, ...attrs}

Timestamps are unix-epoch microseconds derived from a
``perf_counter``-anchored clock captured at stream construction, so
spans from different threads (the prefetcher's producer records from its
own thread) share one monotonic timebase.  The stream is thread-safe and
crash-tolerant: appends are flushed every :data:`FLUSH_EVERY` events and
on ``close()``, which ``TelemetryRun.finalize`` reaches on every path.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time

from .schema import span_event

FLUSH_EVERY = 32


class SpanStream:
    """Append-only ``spans.jsonl`` writer with a shared time anchor."""

    FILENAME = "spans.jsonl"

    def __init__(self, run_dir: str, flush_every: int = FLUSH_EVERY):
        self.path = os.path.join(run_dir, self.FILENAME)
        # one anchor pair: unix epoch at construction + the perf_counter
        # reading at the same instant; every span timestamp is
        # epoch + (perf_now - perf_anchor), monotonic across threads
        self._epoch_us = time.time() * 1e6
        self._perf_anchor = time.perf_counter()
        self._lock = threading.Lock()
        self._f = None
        self._unflushed = 0
        self.flush_every = max(int(flush_every), 1)
        self.spans_written = 0
        self._closed = False

    def _now_us(self) -> float:
        return self._epoch_us + (time.perf_counter()
                                 - self._perf_anchor) * 1e6

    def record(self, name: str, *, start_perf: float, end_perf: float,
               cat: str | None = None, **attrs) -> None:
        """File one completed span given its ``perf_counter`` bounds —
        the form for call sites that already stopwatch themselves (the
        serving engine's burst timers)."""
        ts = self._epoch_us + (start_perf - self._perf_anchor) * 1e6
        self._append(span_event(name, ts_us=ts,
                                dur_us=(end_perf - start_perf) * 1e6,
                                cat=cat, **attrs))

    @contextlib.contextmanager
    def span(self, name: str, cat: str | None = None, **attrs):
        """Context-manager form: times the body, files on exit (also on
        exception — a crashed wait still shows in the timeline)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, start_perf=t0,
                        end_perf=time.perf_counter(), cat=cat, **attrs)

    # ---- file plumbing --------------------------------------------------
    def _append(self, ev: dict) -> None:
        with self._lock:
            if self._closed:
                return
            if self._f is None:
                os.makedirs(os.path.dirname(self.path), exist_ok=True)
                self._f = open(self.path, "a")
            self._f.write(json.dumps(ev, default=str) + "\n")
            self.spans_written += 1
            self._unflushed += 1
            if self._unflushed >= self.flush_every:
                self._f.flush()
                self._unflushed = 0

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._f is not None:
                self._f.flush()
                self._f.close()
                self._f = None


@contextlib.contextmanager
def maybe_span(stream, name: str, cat: str | None = None, **attrs):
    """``stream.span(...)`` when a stream is wired, no-op when ``stream``
    is None — the guard every runtime call site uses so spans never
    impose a telemetry dependency."""
    if stream is None:
        yield
        return
    with stream.span(name, cat=cat, **attrs):
        yield


def read_spans(run_dir: str) -> list[dict]:
    """Parse ``<run_dir>/spans.jsonl`` (missing file -> empty list)."""
    path = os.path.join(run_dir, SpanStream.FILENAME)
    out = []
    if os.path.isfile(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue
    return out
