"""Cross-run report: discovery, side-by-side table, regression deltas.

Library behind ``scripts/report.py``.  Reads the run directories the
telemetry layer writes (``manifest.json`` + ``steps.jsonl`` +
``summary.json``) and renders the strategy × payload-shape comparison
table — step time, tokens/s, comm %, per-step collective counts — that
BASELINE.md's NCCL-vs-ICI goal needs on the ICI side.

Baselines for the regression check come in two shapes:
  * another run dir / runs root / ``summary.json`` (same schema), or
  * a bench-style JSON (``bench_matrix_tpu.json``'s ``{"matrix": [...]}``
    rows, a bare row list, or a ``BENCH_*.json`` driver artifact whose
    ``tail`` string embeds the row list) — field aliases are normalized
    (``step_ms``/``step_time_ms``, ``tokens_per_sec``/``tokens_per_second``).
"""

from __future__ import annotations

import json
import os
from typing import Any

# identity fields a row may carry; two rows are comparable when every
# field PRESENT IN BOTH matches and at least one name-ish field does
_IDENTITY = ("strategy", "config", "model", "sequence_length",
             "batch_size", "device_count")
_ALIASES = {
    "step_ms": "step_time_ms",
    "tokens_per_sec": "tokens_per_second",
    "seq_len": "sequence_length",
    "seq": "sequence_length",
    "batch": "batch_size",
    "devices": "device_count",
    "num_devices": "device_count",
}


# --------------------------------------------------------------- discovery

def _is_run_dir(path: str) -> bool:
    return any(os.path.isfile(os.path.join(path, f))
               for f in ("manifest.json", "summary.json"))


def discover_runs(paths: list[str]) -> list[dict]:
    """Each path may be one run dir or a root of run dirs.  Returns one
    record per run: ``{"dir", "manifest", "summary", "num_steps"}``,
    sorted by run dir name (timestamps sort chronologically)."""
    dirs: list[str] = []
    for p in paths:
        if _is_run_dir(p):
            dirs.append(p)
        elif os.path.isdir(p):
            dirs += sorted(os.path.join(p, d) for d in os.listdir(p)
                           if _is_run_dir(os.path.join(p, d)))
    runs = []
    for d in sorted(dict.fromkeys(dirs)):
        rec: dict = {"dir": d, "manifest": None, "summary": None,
                     "num_steps": 0}
        for name, key in (("manifest.json", "manifest"),
                          ("summary.json", "summary")):
            f = os.path.join(d, name)
            if os.path.isfile(f):
                try:
                    rec[key] = json.load(open(f))
                except (OSError, json.JSONDecodeError):
                    pass
        steps = os.path.join(d, "steps.jsonl")
        if os.path.isfile(steps):
            with open(steps) as f:
                rec["num_steps"] = sum(1 for line in f if line.strip())
        runs.append(rec)
    return runs


def load_steps(run_dir: str) -> list[dict]:
    out = []
    path = os.path.join(run_dir, "steps.jsonl")
    if os.path.isfile(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue
    return out


# ----------------------------------------------------------- normalization

def _normalize(row: dict) -> dict:
    out = {}
    for k, v in row.items():
        out[_ALIASES.get(k, k)] = v
    return out


def run_row(rec: dict) -> dict:
    """Flatten one discovered run record into a normalized metrics row."""
    man = rec.get("manifest") or {}
    summ = dict(rec.get("summary") or {})
    cfg = man.get("config") or {}
    row: dict[str, Any] = {
        "run_id": man.get("run_id") or summ.get("run_id")
        or os.path.basename(rec["dir"]),
        "dir": rec["dir"],
        "strategy": summ.get("strategy") or man.get("strategy") or "?",
        "model": summ.get("model") or man.get("model"),
        "sequence_length": summ.get("sequence_length")
        or cfg.get("sequence_length"),
        "batch_size": summ.get("batch_size") or cfg.get("batch_size"),
        "device_count": man.get("device_count"),
        "platform": man.get("platform"),
        "status": summ.get("status", "?"),
        "num_steps": rec.get("num_steps", 0),
        "collective_counts": man.get("collective_counts"),
        # choreography-contract verdict (analysis.evaluate_contract),
        # recorded by the strategy scripts since manifests grew the field
        "contract_ok": (man.get("contract") or {}).get("ok"),
        # restart lineage (resilience.supervisor): present only on runs
        # that ran under an active supervisor — rendered as stitched
        # segments below the main table
        "lineage": man.get("lineage"),
    }
    # memory planner record (scripts record it in manifest extra):
    # predicted analytic waterline, the compiler-reported one when the
    # run was planned, and the budget it was judged against
    mp = (man.get("extra") or {}).get("memory_plan") or {}
    for src, dst in (("predicted_gb", "predicted_gb"),
                     ("compiled_gb", "compiled_gb"),
                     ("budget_gb", "hbm_budget_gb"),
                     ("auto_fit", "auto_fit")):
        if mp.get(src) is not None:
            row[dst] = mp[src]
    for k in ("step_time_ms", "tokens_per_second", "tflops_per_device",
              "avg_loss", "final_loss", "peak_memory_gb"):
        if summ.get(k) is not None:
            row[k] = summ[k]
    sp = summ.get("comm_split") or {}
    if sp.get("comm_fraction") is not None:
        row["comm_fraction"] = sp["comm_fraction"]
    if sp.get("overlap_fraction") is not None:
        row["overlap_fraction"] = sp["overlap_fraction"]
    if summ.get("host_sync_count") is not None:
        row["host_sync_count"] = summ["host_sync_count"]
    # tuner verdict (tuner.plan_manifest_stamp): present only on runs
    # that replayed a plan via --plan — rendered as its own section so
    # every replay is traceable back to the plan that chose its knobs
    tuner = (man.get("extra") or {}).get("tuner") \
        or cfg.get("tuner") or summ.get("tuner")
    if tuner is not None:
        row["tuner"] = tuner
    # serving SLO block (serving.ServingEngine.slo_report, filed by
    # scripts/serve_bench.py) — rendered as its own section
    if summ.get("serving") is not None:
        row["serving"] = summ["serving"]
    # fleet block (serving.Fleet.slo_report, filed by serve_bench
    # --replicas N): per-replica SLO + the failover/swap event timeline
    if summ.get("fleet") is not None:
        row["fleet"] = summ["fleet"]
    # simulator block (sim.SimFleet.slo_report, filed by
    # scripts/sim_bench.py): virtual-clock fleet run — per-tenant
    # fairness + attainment curves; substrate-tagged so it never joins
    # a wall-clock comparison silently
    if summ.get("sim") is not None:
        row["sim"] = summ["sim"]
        if summ.get("sim_variants") is not None:
            row["sim_variants"] = summ["sim_variants"]
    # collective ledger (telemetry.ledger): measured contract verdict +
    # bus bandwidth from the compact manifest/summary block, per-(kind,
    # payload, axis) aggregates from the run dir's collectives.json —
    # the ICI side of the NCCL-vs-ICI table and the bandwidth gate
    led = summ.get("ledger") or man.get("ledger") or {}
    if led:
        if "ok" in led:
            row["ledger_ok"] = led.get("ok")
        if led.get("busbw_gbps") is not None:
            row["ledger_busbw_gbps"] = led.get("busbw_gbps")
    from .ledger import load_ledger_dict
    ld = load_ledger_dict(rec["dir"])
    if ld:
        row["ledger_aggregates"] = ld.get("aggregates") or {}
        tot = ld.get("totals") or {}
        if tot.get("busbw_gbps") is not None:
            row.setdefault("ledger_busbw_gbps", tot["busbw_gbps"])
        cj = ld.get("contract_join") or {}
        if "ok" in cj:
            row.setdefault("ledger_ok", cj["ok"])
    # memory ledger (telemetry.memledger): the MemoryVerdict — measured
    # allocator peak vs compiled memory_analysis() waterline vs planner
    # prediction — plus flattened per-category aggregates from
    # memory.json, feeding the measured-vs-predicted table and the
    # --fail-on-memory-regression gate
    mv = summ.get("memory") or man.get("memory") or {}
    if mv:
        row["memory_verdict"] = mv
        if "ok" in mv:
            row["memory_ok"] = mv.get("ok")
        if mv.get("measured_gb") is not None:
            row["measured_peak_gb"] = mv["measured_gb"]
    from .memledger import load_memory_dict, memory_aggregates
    md = load_memory_dict(rec["dir"])
    if md:
        row["memory_aggregates"] = memory_aggregates(md)
    return row


def load_baseline_rows(path: str) -> list[dict]:
    """Normalize any supported baseline source into metric rows."""
    if os.path.isdir(path):
        return [run_row(rec) for rec in discover_runs([path])]
    try:
        data = json.load(open(path))
    except (OSError, json.JSONDecodeError):
        return []
    if isinstance(data, list):
        rows = data
    elif isinstance(data, dict):
        if os.path.basename(path) == "summary.json":
            return [run_row({"dir": os.path.dirname(path) or ".",
                             "manifest": None, "summary": data,
                             "num_steps": 0})]
        rows = data.get("matrix") or data.get("rows")
        if rows is None and isinstance(data.get("tail"), str):
            rows = _rows_from_tail(data["tail"])
        if rows is None:
            rows = [data]
    else:
        return []
    return [_normalize(r) for r in rows if isinstance(r, dict)]


def _rows_from_tail(tail: str) -> list[dict]:
    """Best-effort recovery of the row list a BENCH_*.json driver
    artifact embeds in its truncated ``tail`` log text: parse every
    balanced {...} object and keep the ones that look like metric rows."""
    rows, depth, start = [], 0, None
    for i, ch in enumerate(tail):
        if ch == "{":
            if depth == 0:
                start = i
            depth += 1
        elif ch == "}" and depth:
            depth -= 1
            if depth == 0 and start is not None:
                try:
                    obj = json.loads(tail[start:i + 1])
                except json.JSONDecodeError:
                    continue
                if isinstance(obj, dict) and (
                        "tokens_per_sec" in obj or "step_ms" in obj
                        or "tokens_per_second" in obj
                        or "step_time_ms" in obj):
                    rows.append(obj)
    return rows


# ----------------------------------------------------------------- table

def _fmt(v, spec=".1f") -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        return format(v, spec)
    return str(v)


def _mem_cell(r: dict) -> str:
    """Memory column: the memory ledger's measured peak when one was
    filed (measured beats modeled), else the compiler-reported waterline
    when the run was planned, else the analytic prediction (``~``
    prefix), else the tracker's sampled allocator peak; budget appended
    when one gated the run."""
    if r.get("measured_peak_gb") is not None:
        cell = _fmt(float(r["measured_peak_gb"]), ".2f")
    elif r.get("compiled_gb") is not None:
        cell = _fmt(float(r["compiled_gb"]), ".2f")
    elif r.get("predicted_gb") is not None:
        cell = "~" + _fmt(float(r["predicted_gb"]), ".2f")
    elif r.get("peak_memory_gb") is not None:
        cell = _fmt(float(r["peak_memory_gb"]), ".2f")
    else:
        return "—"
    if r.get("hbm_budget_gb") is not None:
        cell += f"/{float(r['hbm_budget_gb']):.1f}"
    return cell


def render_table(rows: list[dict]) -> str:
    """Strategy × payload-shape side-by-side markdown table."""
    if not rows:
        return "_no runs found_"
    out = ["| run | strategy | model | seq | batch | dev | steps | "
           "step ms | tok/s | TFLOPS/dev | mem GB | comm % | overlap % | "
           "host syncs | collectives/step | status |",
           "|---|---|---|---|---|---|---|---|---|---|---|---|---|---|---|"
           "---|"]
    for r in sorted(rows, key=lambda r: (r.get("strategy") or "",
                                         str(r.get("model")),
                                         r.get("run_id") or "")):
        cc = r.get("collective_counts") or {}
        cc_cell = str(cc.get("total")) if cc else "—"
        # annotate with the contract verdict when one was recorded
        if r.get("contract_ok") is True:
            cc_cell += " ✓"
        elif r.get("contract_ok") is False:
            cc_cell += " ✗"
        # second mark: the trace-measured ledger verdict, when one ran
        if r.get("ledger_ok") is True:
            cc_cell += "⋈✓"
        elif r.get("ledger_ok") is False:
            cc_cell += "⋈✗"
        # third mark: the memory ledger's measured-waterline verdict
        if r.get("memory_ok") is True:
            cc_cell += "▦✓"
        elif r.get("memory_ok") is False:
            cc_cell += "▦✗"
        comm = r.get("comm_fraction")
        ovl = r.get("overlap_fraction")
        out.append(
            f"| {r.get('run_id', '—')} | {r.get('strategy', '—')} "
            f"| {r.get('model') or '—'} "
            f"| {r.get('sequence_length') or '—'} "
            f"| {r.get('batch_size') or '—'} "
            f"| {r.get('device_count') or '—'} "
            f"| {r.get('num_steps') or '—'} "
            f"| {_fmt(r.get('step_time_ms'), '.2f')} "
            f"| {_fmt(r.get('tokens_per_second'), '.0f')} "
            f"| {_fmt(r.get('tflops_per_device'), '.2f')} "
            f"| {_mem_cell(r)} "
            f"| {_fmt(100 * comm if comm is not None else None, '.1f')} "
            f"| {_fmt(100 * ovl if ovl is not None else None, '.1f')} "
            f"| {_fmt(r.get('host_sync_count'), 'd')} "
            f"| {cc_cell} | {r.get('status', '—')} |")
    return "\n".join(out)


# ---------------------------------------------------------------- serving

def render_serving(rows: list[dict]) -> str:
    """Latency-SLO table for every run that filed a ``serving`` block
    (``serving.ServingEngine.slo_report`` via ``scripts/serve_bench.py``):
    TTFT / per-token percentiles, throughput per device, pool and
    scheduler health, and the recompile watch's verdict."""
    srows = [r for r in rows if r.get("serving")]
    if not srows:
        return "_no serving runs_"
    out = ["| run | reqs | done | TTFT p50/p99 ms | tok p50/p99 ms | "
           "tok/s | tok/s/dev | occ | pool peak | cache hit | "
           "spec acc | retraces | mode |",
           "|---|---|---|---|---|---|---|---|---|---|---|---|---|"]
    for r in sorted(srows, key=lambda r: r.get("run_id") or ""):
        s = r["serving"]
        ttft = s.get("ttft_ms") or {}
        ptl = s.get("per_token_ms") or {}
        sched = s.get("scheduler") or {}
        pool = s.get("pool") or {}
        rt = s.get("recompiles_after_warmup")
        mode = "disagg" if s.get("disaggregated") else "unified"
        if s.get("kv_quant"):
            mode += "+kvq"
        if s.get("flash_prefill"):
            mode += "+flash"
        pc = s.get("prefix_cache") or {}
        sp = s.get("speculative") or {}
        hit = (f"{100 * pc['hit_rate']:.0f}%"
               if pc.get("hit_rate") is not None else "—")
        acc = (f"{100 * sp['acceptance_rate']:.0f}% (k={sp.get('k')})"
               if sp.get("acceptance_rate") is not None else "—")
        out.append(
            f"| {r.get('run_id', '—')} "
            f"| {_fmt(s.get('requests'), 'd')} "
            f"| {_fmt(s.get('completed'), 'd')} "
            f"| {_fmt(ttft.get('p50'), '.1f')}/{_fmt(ttft.get('p99'), '.1f')} "
            f"| {_fmt(ptl.get('p50'), '.2f')}/{_fmt(ptl.get('p99'), '.2f')} "
            f"| {_fmt(s.get('tokens_per_s'), '.1f')} "
            f"| {_fmt(s.get('tokens_per_s_per_device'), '.2f')} "
            f"| {_fmt(sched.get('mean_occupancy'), '.2f')} "
            f"| {_fmt(pool.get('peak_util'), '.2f')} "
            f"| {hit} "
            f"| {acc} "
            f"| {'0 ✓' if rt == 0 else _fmt(rt, 'd') if rt is not None else '—'} "
            f"| {mode} |")
    return "\n".join(out)


# ----------------------------------------------------------------- tuner

def render_tuner(rows: list[dict]) -> str:
    """Tuner-verdict table for every run that replayed a plan
    (``tuner.plan_manifest_stamp`` stamped via a driver's ``--plan``):
    the chosen candidate, the plan's provenance hashes, and predicted
    vs this run's numbers — the closed loop made visible."""
    trows = [r for r in rows if r.get("tuner")]
    if not trows:
        return "_no plan-replayed runs_"
    out = ["| run | plan | objective | chosen | knob space | cost model "
           "| predicted tok/s | plan-measured tok/s | this run tok/s |",
           "|---|---|---|---|---|---|---|---|---|"]
    for r in sorted(trows, key=lambda r: r.get("run_id") or ""):
        t = r["tuner"]
        pred = t.get("predicted") or {}
        meas = t.get("measured") or {}
        out.append(
            f"| {r.get('run_id', '—')} "
            f"| {t.get('plan') or '—'} "
            f"| {t.get('objective') or '—'} "
            f"| {t.get('chosen') or '—'} "
            f"| {t.get('knob_space_hash') or '—'} "
            f"| {t.get('cost_model_hash') or '—'} "
            f"| {_fmt(pred.get('predicted_tokens_per_sec'), '.1f')} "
            f"| {_fmt(meas.get('tokens_per_sec'), '.1f')} "
            f"| {_fmt(r.get('tokens_per_second'), '.1f')} |")
    return "\n".join(out)


# ----------------------------------------------------------------- fleet

def render_fleet(rows: list[dict]) -> str:
    """Per-replica SLO table + event timeline for every run that filed
    a ``fleet`` block (``serving.Fleet.slo_report`` via ``serve_bench
    --replicas N``).  One row per replica so a dead replica's partial
    service and its survivors' absorbed load sit side by side; below
    each run, the failover/shed/swap event timeline."""
    frows = [r for r in rows if r.get("fleet")]
    if not frows:
        return "_no fleet runs_"
    out = ["| run | replica | state | reqs | done | TTFT p50/p99 ms | "
           "tok p50/p99 ms | tok/s | bursts | retraces |",
           "|---|---|---|---|---|---|---|---|---|---|"]
    lines = []
    for r in sorted(frows, key=lambda r: r.get("run_id") or ""):
        f = r["fleet"]
        ttft = f.get("ttft_ms") or {}
        ptl = f.get("per_token_ms") or {}
        rt = f.get("recompiles_after_warmup")
        out.append(
            f"| {r.get('run_id', '—')} | **fleet** "
            f"| {f.get('live', '—')}/{f.get('replicas', '—')} live "
            f"| {_fmt(f.get('submitted'), 'd')} "
            f"| {_fmt(f.get('completed'), 'd')} "
            f"| {_fmt(ttft.get('p50'), '.1f')}/{_fmt(ttft.get('p99'), '.1f')} "
            f"| {_fmt(ptl.get('p50'), '.2f')}/{_fmt(ptl.get('p99'), '.2f')} "
            f"| — | — "
            f"| {'0 ✓' if rt == 0 else _fmt(rt, 'd') if rt is not None else '—'} |")
        for s in f.get("replica_slo") or []:
            sttft = s.get("ttft_ms") or {}
            sptl = s.get("per_token_ms") or {}
            srt = s.get("recompiles_after_warmup")
            state = s.get("state", "?")
            if s.get("death"):
                state += f" ({s['death']})"
            out.append(
                f"| {r.get('run_id', '—')} | {s.get('replica', '—')} "
                f"| {state} "
                f"| {_fmt(s.get('requests'), 'd')} "
                f"| {_fmt(s.get('completed'), 'd')} "
                f"| {_fmt(sttft.get('p50'), '.1f')}/{_fmt(sttft.get('p99'), '.1f')} "
                f"| {_fmt(sptl.get('p50'), '.2f')}/{_fmt(sptl.get('p99'), '.2f')} "
                f"| {_fmt(s.get('tokens_per_s'), '.1f')} "
                f"| {_fmt(s.get('bursts'), 'd')} "
                f"| {'0 ✓' if srt == 0 else _fmt(srt, 'd') if srt is not None else '—'} |")
        shed = f.get("shed", 0)
        drop = f.get("dropped", 0)
        ev = f.get("events") or []
        tl = "; ".join(
            f"{e.get('t_s', '?')}s {e.get('event', '?')}"
            + (f" r{e['replica']}" if "replica" in e else "")
            + (f" ({e['trigger']})" if "trigger" in e else "")
            for e in ev) or "none"
        lines.append(f"- `{r.get('run_id', '—')}`: shed {shed}, "
                     f"dropped {drop}"
                     + (" ⚠" if drop else " ✓")
                     + f"; events: {tl}")
    return "\n".join(out) + "\n\n" + "\n".join(lines)


# ------------------------------------------------------------------- sim

def render_sim(rows: list[dict]) -> str:
    """Virtual-clock fleet runs (``sim.SimFleet.slo_report`` via
    ``scripts/sim_bench.py``): the fleet-scale numbers only the
    simulator can afford — per-tenant SLO attainment and fairness over
    10^5+ offered requests — plus the policy-variant ranking when the
    run evaluated one.  All times are VIRTUAL seconds priced by the
    run's calibrated cost model (``cost_model.source`` says which
    measured run priced them)."""
    srows = [r for r in rows if r.get("sim")]
    if not srows:
        return "_no simulator runs_"
    out = ["| run | offered | done | shed | TTFT p50/p99 ms | "
           "SLO ms | attained | Jain | worst tenant | cost model |",
           "|---|---|---|---|---|---|---|---|---|---|"]
    lines = []
    for r in sorted(srows, key=lambda r: r.get("run_id") or ""):
        s = r["sim"]
        ttft = s.get("ttft_ms") or {}
        fair = s.get("fairness") or {}
        worst = fair.get("worst_tenant") or {}
        att = s.get("attainment") or {}
        # overall attainment at the report's SLO threshold: nearest
        # grid point at or above slo_ms
        overall = None
        th = att.get("thresholds_ms") or []
        cur = att.get("overall") or []
        slo = s.get("slo_ms")
        if th and cur and slo is not None:
            idx = min((i for i, g in enumerate(th) if g >= slo),
                      default=len(th) - 1)
            overall = cur[idx]
        cm = (s.get("cost_model") or {}).get("source", "—")
        out.append(
            f"| {r.get('run_id', '—')} "
            f"| {_fmt(s.get('offered'), 'd')} "
            f"| {_fmt(s.get('completed'), 'd')} "
            f"| {_fmt(s.get('shed'), 'd')} "
            f"| {_fmt(ttft.get('p50'), '.1f')}/{_fmt(ttft.get('p99'), '.1f')} "
            f"| {_fmt(slo, '.0f')} "
            f"| {_fmt(overall, '.1%')} "
            f"| {_fmt(fair.get('jain_attainment'), '.3f')} "
            f"| t{worst.get('tenant', '—')} @ "
            f"{_fmt(worst.get('attainment'), '.1%')} "
            f"| {cm} |")
        ev = s.get("events") or []
        tl = "; ".join(
            f"{e.get('t_s', '?')}s {e.get('event', '?')}"
            + (f" r{e['replica']}" if "replica" in e else "")
            for e in ev) or "none"
        lines.append(
            f"- `{r.get('run_id', '—')}`: virtual "
            f"{_fmt(s.get('virtual_duration_s'), '.1f')}s on "
            f"{s.get('replicas', '—')} replicas, digest "
            f"`{(s.get('digest') or '—')[:16]}`; events: {tl}")
        for v in r.get("sim_variants") or []:
            vt = v.get("ttft_ms") or {}
            lines.append(
                f"  - variant `{v.get('name')}` "
                f"{v.get('overrides') or {}}: objective "
                f"{_fmt(v.get('objective'), '.1f')}, TTFT p99 "
                f"{_fmt(vt.get('p99'), '.1f')} ms, shed "
                f"{_fmt(v.get('shed'), 'd')}")
    return "\n".join(out) + "\n\n" + "\n".join(lines)


# ---------------------------------------------------------------- lineage

def _fmt_segment(seg: dict) -> str:
    span = f"{seg.get('start_step', '?')}..{seg.get('end_step', '?')}"
    scope = f"{seg['scope']}:" if seg.get("scope") else ""
    return f"[{scope}{span} {seg.get('status', '?')}]"


def _fmt_transition(tr: dict) -> str:
    lost = tr.get("lost_ranks") or []
    at = (f" at step {tr['step']}" if tr.get("step") is not None else "")
    why = tr.get("trigger", "?")
    who = f", lost ranks {lost}" if lost else ""
    return (f"{tr.get('old_world', '?')} → {tr.get('new_world', '?')} "
            f"({why}{who}{at})")


def render_lineage(rows: list[dict]) -> str:
    """Stitched-segment view of every run whose manifest carries restart
    lineage: the prior segments' spans/status chained into this run,
    plus where it resumed, whether the collective contract re-check
    passed on restore, and — for elastic runs — the mesh transitions
    (old/new world size, trigger, lost ranks)."""
    out = []
    for r in rows:
        lin = r.get("lineage") or {}
        if not lin:
            continue
        segs = [s for s in (lin.get("segments") or [])
                if isinstance(s, dict)]
        chain = " → ".join(_fmt_segment(s) for s in segs) if segs else ""
        transitions = [t for t in (lin.get("mesh_transitions") or [])
                       if isinstance(t, dict)]
        scopes = [("", lin)] + sorted((lin.get("scopes") or {}).items())
        resumed = []
        for label, sc in scopes:
            if not isinstance(sc, dict) or sc.get("resumed_from_step") \
                    is None:
                continue
            rc = sc.get("resume_contract") or {}
            mark = " contract ✓" if rc.get("ok") is True \
                else " contract ✗" if rc.get("ok") is False else ""
            resumed.append(
                f"{label + ' ' if label else ''}resumed from step "
                f"{sc['resumed_from_step']}{mark}")
        line = (f"- **{r.get('run_id', '?')}** "
                f"(attempt {lin.get('attempt', 0)}"
                f"/{lin.get('max_restarts', 0)} restarts)")
        if resumed:
            line += ": " + "; ".join(resumed)
        if chain:
            line += f"\n  - segments: {chain} → this run"
        if transitions:
            line += ("\n  - mesh transitions (elastic): "
                     + "; ".join(_fmt_transition(t) for t in transitions))
        out.append(line)
    return "\n".join(out) if out else "_no runs with restart lineage_"


def render_chaos(doc: dict) -> str:
    """Campaign table for one ``chaos_report.json`` (scripts/chaos.py):
    the (fault x strategy) matrix with per-cell verdicts and, for red
    cells, which invariant broke."""
    cells = [c for c in (doc.get("cells") or []) if isinstance(c, dict)]
    if not cells:
        return "_no chaos cells in report_"
    out = [f"| {'cell':24} | {'fault':13} | {'strategy':8} | "
           f"{'status':6} | {'dur_s':>6} | invariants |",
           f"|{'-' * 26}|{'-' * 15}|{'-' * 10}|{'-' * 8}|{'-' * 8}|"
           f"{'-' * 12}|"]
    for c in cells:
        inv = c.get("invariants") or {}
        bad = [k for k, v in inv.items() if not v]
        mark = "✓ " + f"{len(inv)}/{len(inv)}" if not bad else \
            "✗ failed: " + ", ".join(bad)
        dur = c.get("duration_s")
        out.append(
            f"| {str(c.get('cell', '?')):24} "
            f"| {str(c.get('fault', '?')):13} "
            f"| {str(c.get('strategy', '?')):8} "
            f"| {str(c.get('status', '?')):6} "
            f"| {dur if dur is not None else '-':>6} | {mark} |")
    s = doc.get("summary") or {}
    out.append(f"\n{s.get('green', '?')}/{s.get('total', '?')} cell(s) "
               f"green"
               + (f" — {s.get('red')} RED" if s.get("red") else ""))
    return "\n".join(out)


# ------------------------------------------------------------ regressions

def _match(cur: dict, base: dict) -> bool:
    name_match = False
    for k in _IDENTITY:
        a, b = cur.get(k), base.get(k)
        if a is None or b is None:
            continue
        if a != b:
            return False
        if k in ("strategy", "config", "model"):
            name_match = True
    return name_match


def check_regressions(current: list[dict], baseline: list[dict],
                      tolerance: float = 0.15) -> list[dict]:
    """Compare each current row against every comparable baseline row.
    A regression is step time above baseline × (1+tol) or tokens/s below
    baseline × (1−tol).  Returns one record per comparison; records with
    ``"regressed": True`` should fail the caller."""
    results = []
    for cur in current:
        for base in baseline:
            if cur is base or not _match(cur, base):
                continue
            for metric, worse_is in (("step_time_ms", "higher"),
                                     ("tokens_per_second", "lower")):
                a, b = cur.get(metric), base.get(metric)
                if a is None or b is None or not b:
                    continue
                delta = a / b - 1.0
                regressed = (delta > tolerance if worse_is == "higher"
                             else delta < -tolerance)
                results.append({
                    "run_id": cur.get("run_id"),
                    "baseline": base.get("run_id") or base.get("config")
                    or base.get("strategy"),
                    "metric": metric,
                    "current": a,
                    "baseline_value": b,
                    "delta": delta,
                    "tolerance": tolerance,
                    "regressed": regressed,
                })
    return results


def check_overlap_regressions(current: list[dict], baseline: list[dict],
                              max_drop_pp: float = 5.0) -> list[dict]:
    """Overlap A/B between comparable rows: for every (current, baseline)
    pair that :func:`_match` accepts and where BOTH carry
    ``overlap_fraction`` (the comm-concurrent-with-compute share from
    ``trace_analysis.CommSplit``), record the overlap delta in
    PERCENTAGE POINTS alongside the step-time delta, flagging
    ``regressed`` when overlap dropped by more than ``max_drop_pp`` pp —
    the CI gate behind ``report.py --fail-on-overlap-regression``."""
    results = []
    for cur in current:
        for base in baseline:
            if cur is base or not _match(cur, base):
                continue
            a, b = cur.get("overlap_fraction"), base.get("overlap_fraction")
            if a is None or b is None:
                continue
            delta_pp = (float(a) - float(b)) * 100.0
            st_cur, st_base = cur.get("step_time_ms"), \
                base.get("step_time_ms")
            step_delta = (st_cur / st_base - 1.0
                          if st_cur and st_base else None)
            results.append({
                "run_id": cur.get("run_id"),
                "baseline": base.get("run_id") or base.get("config")
                or base.get("strategy"),
                "overlap_pct": 100.0 * float(a),
                "baseline_overlap_pct": 100.0 * float(b),
                "overlap_delta_pp": delta_pp,
                "step_time_ms": st_cur,
                "baseline_step_time_ms": st_base,
                "step_time_delta": step_delta,
                "max_drop_pp": max_drop_pp,
                "regressed": delta_pp < -max_drop_pp,
            })
    return results


def render_overlap_deltas(results: list[dict]) -> str:
    if not results:
        return "_no comparable rows carry overlap data (profile-enabled " \
               "runs write comm_split.overlap_fraction into summary.json)_"
    out = ["| run | baseline | overlap % | base overlap % | Δ pp | "
           "step ms | base step ms | Δ step | verdict |",
           "|---|---|---|---|---|---|---|---|---|"]
    for r in results:
        sd = r.get("step_time_delta")
        out.append(
            f"| {r['run_id']} | {r['baseline']} "
            f"| {_fmt(r['overlap_pct'], '.1f')} "
            f"| {_fmt(r['baseline_overlap_pct'], '.1f')} "
            f"| {r['overlap_delta_pp']:+.1f} "
            f"| {_fmt(r.get('step_time_ms'), '.2f')} "
            f"| {_fmt(r.get('baseline_step_time_ms'), '.2f')} "
            f"| {f'{sd:+.1%}' if sd is not None else '—'} "
            f"| {'REGRESSED' if r['regressed'] else 'ok'} |")
    return "\n".join(out)


# ------------------------------------------------------- bus bandwidth

# ledger kinds use count_collectives spelling; busbench / NCCL tables
# call the permute "ppermute"
_KIND_ALIASES = {"collective_permute": "ppermute"}


def load_nccl_reference(path: str) -> list[dict]:
    """Rows of ``baselines/nccl_reference.json``: one record per
    (hardware, collective) with the reference busbw in GB/s.  Accepts the
    dict form (``{"rows": [...]}``) or a bare list."""
    try:
        data = json.load(open(path))
    except (OSError, json.JSONDecodeError):
        return []
    rows = data.get("rows") if isinstance(data, dict) else data
    return [r for r in (rows or []) if isinstance(r, dict)]


def load_roofline(path: str) -> list[dict]:
    """Rows of a ``scripts/busbench.py`` sweep JSON (the measured
    microbenchmark roofline).  Accepts the dict form (``{"platform",
    "rows": [...]}``) or the legacy bare row list; a platform tag is
    stamped onto each row when the file carries one."""
    try:
        data = json.load(open(path))
    except (OSError, json.JSONDecodeError):
        return []
    if isinstance(data, dict):
        rows = [r for r in (data.get("rows") or []) if isinstance(r, dict)]
        plat = data.get("platform")
        if plat:
            for r in rows:
                r.setdefault("platform", plat)
        return rows
    return [r for r in data if isinstance(r, dict)]


def _best_busbw(rows: list[dict], kind: str) -> float | None:
    """Peak busbw over a row set for one collective kind — the roofline
    reading (best payload size wins)."""
    name = _KIND_ALIASES.get(kind, kind)
    vals = [r.get("busbw_gbps") for r in rows
            if r.get("collective") in (name, kind)
            and r.get("busbw_gbps") is not None]
    return max(vals) if vals else None


def render_bandwidth_table(rows: list[dict],
                           nccl_rows: list[dict] | None = None,
                           roofline_rows: list[dict] | None = None) -> str:
    """The NCCL-vs-ICI side-by-side: every ledger aggregate (collective
    kind × payload bucket × mesh axis) of every run that filed a
    ``collectives.json``, beside the local busbench roofline (same
    accounting, microbenchmark conditions) and the NCCL reference
    hardware numbers."""
    lrows = [r for r in rows if r.get("ledger_aggregates")]
    if not lrows:
        return "_no runs carry a collective ledger (profile-enabled " \
               "runs with an attached HLO write collectives.json)_"
    nccl_rows = nccl_rows or []
    roofline_rows = roofline_rows or []
    out = ["| run | collective | payload | axis | sites | events | "
           "mean µs | busbw GB/s | roofline GB/s | NCCL ref GB/s |",
           "|---|---|---|---|---|---|---|---|---|---|"]
    for r in sorted(lrows, key=lambda r: r.get("run_id") or ""):
        verdict = {True: " ⋈✓", False: " ⋈✗"}.get(r.get("ledger_ok"), "")
        first = True
        for key, a in sorted(r["ledger_aggregates"].items()):
            kind = a.get("kind", key.split("|")[0])
            roof = _best_busbw(roofline_rows, kind)
            nccl = [f"{n.get('hardware', '?')} {n['busbw_gbps']:.0f}"
                    for n in nccl_rows
                    if n.get("collective") in (
                        _KIND_ALIASES.get(kind, kind), kind)
                    and n.get("busbw_gbps") is not None]
            mean_us = (a["total_us"] / a["events"]) if a.get("events") \
                else None
            run_cell = (r.get("run_id", "—") + verdict) if first else "↳"
            first = False
            out.append(
                f"| {run_cell} | {kind} | {a.get('payload_bucket', '—')} "
                f"| {a.get('axis', '—')} | {_fmt(a.get('sites'), 'd')} "
                f"| {_fmt(a.get('events'), 'd')} "
                f"| {_fmt(mean_us, '.1f')} "
                f"| {_fmt(a.get('busbw_gbps'), '.3f')} "
                f"| {_fmt(roof, '.3f')} "
                f"| {', '.join(nccl) if nccl else '—'} |")
    return "\n".join(out)


def check_bandwidth_regressions(current: list[dict], baseline: list[dict],
                                max_drop_pct: float = 20.0) -> list[dict]:
    """Bandwidth gate between comparable rows: for every (current,
    baseline) pair :func:`_match` accepts where BOTH carry ledger
    aggregates, diff each shared (kind, payload bucket, axis) key's
    busbw via ``ledger.check_bandwidth_regressions`` — the CI gate
    behind ``report.py --fail-on-bandwidth-regression``."""
    from .ledger import check_bandwidth_regressions as _diff
    results = []
    for cur in current:
        for base in baseline:
            if cur is base or not _match(cur, base):
                continue
            ca, ba = cur.get("ledger_aggregates"), \
                base.get("ledger_aggregates")
            if not ca or not ba:
                continue
            results += _diff(ca, ba, max_drop_pct=max_drop_pct,
                             label=cur.get("run_id"),
                             base_label=base.get("run_id")
                             or base.get("strategy"))
    return results


def render_bandwidth_regressions(results: list[dict]) -> str:
    if not results:
        return "_no comparable rows carry ledger aggregates (both sides " \
               "need a collectives.json)_"
    out = ["| run | baseline | collective\\|payload\\|axis | busbw GB/s | "
           "base GB/s | Δ % | verdict |",
           "|---|---|---|---|---|---|---|"]
    for r in results:
        key = r["key"].replace("|", "\\|")
        out.append(
            f"| {r['run_id']} | {r['baseline']} "
            f"| {key} "
            f"| {_fmt(r['busbw_gbps'], '.3f')} "
            f"| {_fmt(r['baseline_busbw_gbps'], '.3f')} "
            f"| {r['delta_pct']:+.1f} "
            f"| {'REGRESSED' if r['regressed'] else 'ok'} |")
    return "\n".join(out)


# ------------------------------------------------------------- memory

def render_memory_table(rows: list[dict]) -> str:
    """The measured-vs-predicted waterline side-by-side: every run that
    filed a memory ledger (``memory.json`` + the MemoryVerdict), with the
    measured allocator peak, its source tier (``allocator`` on real HBM,
    ``accounted`` on the CPU sim where the backend exposes no stats),
    the compiled ``memory_analysis()`` waterline, the driver's planner
    prediction, and the biggest attributed categories."""
    mrows = [r for r in rows if r.get("memory_verdict")]
    if not mrows:
        return "_no runs carry a memory ledger (profile-enabled runs " \
               "with an attached step HLO write memory.json)_"
    out = ["| run | measured GB | source | compiled GB | ratio | "
           "predicted GB | pred source | top categories | verdict |",
           "|---|---|---|---|---|---|---|---|---|"]
    for r in sorted(mrows, key=lambda r: r.get("run_id") or ""):
        v = r["memory_verdict"]
        cats = {k[4:]: gb for k, gb in
                (r.get("memory_aggregates") or {}).items()
                if k.startswith("cat/")}
        top = ", ".join(f"{k} {gb:.3f}" for k, gb in
                        sorted(cats.items(), key=lambda kv: -kv[1])[:3])
        out.append(
            f"| {r.get('run_id', '—')} "
            f"| {_fmt(v.get('measured_gb'), '.3f')} "
            f"| {v.get('measured_source', '—')} "
            f"| {_fmt(v.get('compiled_gb'), '.3f')} "
            f"| {_fmt(v.get('compiled_ratio'), '.2f')} "
            f"| {_fmt(v.get('predicted_gb'), '.3f')} "
            f"| {v.get('predicted_source', '—')} "
            f"| {top or '—'} "
            f"| {'ok' if v.get('ok') else 'FAIL'} |")
    return "\n".join(out)


def check_memory_regressions(current: list[dict], baseline: list[dict],
                             max_growth_pct: float = 20.0) -> list[dict]:
    """Memory gate between comparable rows: for every (current, baseline)
    pair :func:`_match` accepts where BOTH carry memory aggregates, diff
    each shared key's GB via ``memledger.check_memory_regressions`` —
    growth is the bad direction — the CI gate behind ``report.py
    --fail-on-memory-regression``."""
    from .memledger import check_memory_regressions as _diff
    results = []
    for cur in current:
        for base in baseline:
            if cur is base or not _match(cur, base):
                continue
            ca, ba = cur.get("memory_aggregates"), \
                base.get("memory_aggregates")
            if not ca or not ba:
                continue
            results += _diff(ca, ba, max_growth_pct=max_growth_pct,
                             label=cur.get("run_id"),
                             base_label=base.get("run_id")
                             or base.get("strategy"))
    return results


def render_memory_regressions(results: list[dict]) -> str:
    if not results:
        return "_no comparable rows carry memory aggregates (both sides " \
               "need a memory.json)_"
    out = ["| run | baseline | key | GB | base GB | Δ % | verdict |",
           "|---|---|---|---|---|---|---|"]
    for r in results:
        out.append(
            f"| {r['run_id']} | {r['baseline']} "
            f"| {r['key']} "
            f"| {_fmt(r['gb'], '.4f')} "
            f"| {_fmt(r['baseline_gb'], '.4f')} "
            f"| {r['delta_pct']:+.1f} "
            f"| {'REGRESSED' if r['regressed'] else 'ok'} |")
    return "\n".join(out)


def render_regressions(results: list[dict]) -> str:
    if not results:
        return "_no comparable baseline rows_"
    out = ["| run | baseline | metric | current | baseline | Δ | verdict |",
           "|---|---|---|---|---|---|---|"]
    for r in results:
        out.append(
            f"| {r['run_id']} | {r['baseline']} | {r['metric']} "
            f"| {_fmt(r['current'], '.2f')} "
            f"| {_fmt(r['baseline_value'], '.2f')} "
            f"| {r['delta']:+.1%} "
            f"| {'REGRESSED' if r['regressed'] else 'ok'} |")
    return "\n".join(out)
