"""CollectiveLedger: per-collective bus-bandwidth attribution from traces.

BASELINE.json's metric is "collective bus-bandwidth (GB/s), NCCL vs
ICI"; the static side of that story has existed since the contract
registry (every expected collective site with approximate payload), but
nothing measured those sites on the real timeline.  This module closes
the loop:

  1. ``utils.trace_analysis.collective_event_stats`` extracts one
     record per compiled-HLO collective *instruction* from the
     chrome-trace (trace event names ARE instruction names);
  2. the records are joined against ``ops.hlo.collective_instances`` of
     the same program's compiled text — attaching payload bytes, dtype,
     replica groups and the mesh axis each instruction spans;
  3. achieved algorithm- and bus-bandwidth per instruction follow from
     nccl-tests accounting (``ops.busbench.bus_factor``), aggregated by
     (op kind, pow-2 payload bucket, mesh axis);
  4. the ledger is joined against the strategy's serialized
     ``CollectiveContract`` verdict: every expected site must be
     measured (zero ``missing_from_trace``), nothing measured may be
     outside the program (zero ``unmatched_measured``), and the distinct
     compiled site count must sit in the contract's expected range.

``TelemetryRun.finalize`` writes the result as ``collectives.json`` in
the run dir and lands the measured verdict in ``manifest.json`` beside
the static one; ``scripts/report.py`` renders the NCCL-vs-ICI table
from it and gates on cross-run bandwidth regressions.

Substrate honesty: on the CPU-sim mesh the GB/s numbers measure host
memory choreography — the *join* (every contract site measured, payload
accounting, regression mechanics) is what the tier-1 suite pins; real
ICI GB/s come from the same code path on a multi-chip slice.
"""

from __future__ import annotations

import json
import math
import os
from dataclasses import asdict, dataclass, field

LEDGER_FILENAME = "collectives.json"
LEDGER_SCHEMA_VERSION = 1

# trace-event names ending in "-done" are the wait half of an async
# collective pair: no matching parseable instruction payload (the
# "-start" op carries it), so their time is pooled, never "unmatched"
_DONE_SUFFIXES = ("-done",)


def payload_bucket(nbytes: int) -> str:
    """Pow-2 payload bucket label ("≤4KiB", "≤1MiB", ...) — the nccl-tests
    message-size axis, coarse enough to survive shape jitter between
    runs being diffed."""
    if nbytes <= 0:
        return "0B"
    exp = max(math.ceil(math.log2(nbytes)), 0)
    size = 1 << exp
    for unit, scale in (("GiB", 30), ("MiB", 20), ("KiB", 10)):
        if size >= (1 << scale):
            return f"≤{size >> scale}{unit}"
    return f"≤{size}B"


def _axis_for_group(group_size: int, axis_sizes: dict) -> str:
    """Mesh-axis attribution of one replica-group size: the full mesh ->
    "all", exactly one axis of that size -> its name, ambiguous ->
    "a|b", no match -> "?"."""
    ws = int(math.prod(axis_sizes.values())) if axis_sizes else 1
    if group_size == ws and ws > 1:
        multi = [a for a, s in axis_sizes.items() if int(s) > 1]
        if len(multi) == 1:
            return multi[0]
        return "all"
    names = sorted(a for a, s in axis_sizes.items() if int(s) == group_size)
    if len(names) == 1:
        return names[0]
    if names:
        return "|".join(names)
    return "?"


@dataclass
class LedgerEntry:
    """One measured collective instruction: trace stats ⋈ HLO payload."""
    name: str            # HLO instruction name == trace event name
    kind: str            # "all_reduce", ... (count_collectives keys)
    occurrences: int     # trace events (device rows × invocations)
    total_us: float
    mean_us: float       # per-participation mean — the bandwidth basis
    payload_bytes: int   # nccl-tests-sized message (full logical tensor)
    dtype: str = ""
    group_size: int = 1
    axis: str = "?"
    algbw_gbps: float = 0.0
    busbw_gbps: float = 0.0

    def to_dict(self) -> dict:
        return asdict(self)


@dataclass
class CollectiveLedger:
    entries: list[LedgerEntry] = field(default_factory=list)
    # collective-named trace events with no instruction in the program
    # (concurrent run's trace, or a parse gap) — any entry here fails
    # the contract join
    unmatched_events: dict = field(default_factory=dict)
    # program collectives that never appeared in the trace (profiler
    # window missed them, or the trace belongs to another program)
    unmeasured_instances: list = field(default_factory=list)
    async_done_us: float = 0.0
    axis_sizes: dict = field(default_factory=dict)
    contract_join: dict | None = None

    # ---- derived --------------------------------------------------------
    def sites_by_kind(self, measured_only: bool = True) -> dict[str, int]:
        """Distinct instruction count per kind.  With
        ``measured_only=False`` the unmeasured program instructions are
        included — that total is what the contract range is checked
        against."""
        out: dict[str, int] = {}
        for e in self.entries:
            out[e.kind] = out.get(e.kind, 0) + 1
        if not measured_only:
            for rec in self.unmeasured_instances:
                k = rec["kind"] if isinstance(rec, dict) else rec.kind
                out[k] = out.get(k, 0) + 1
        return out

    def aggregates(self) -> dict[str, dict]:
        """(kind, payload bucket, axis) -> pooled stats.  Bandwidth is
        time-weighted over the pooled events (total bytes over total
        time), not a mean of means."""
        out: dict[str, dict] = {}
        for e in self.entries:
            key = f"{e.kind}|{payload_bucket(e.payload_bytes)}|{e.axis}"
            a = out.setdefault(key, {
                "kind": e.kind,
                "payload_bucket": payload_bucket(e.payload_bytes),
                "axis": e.axis, "sites": 0, "events": 0,
                "total_us": 0.0, "bytes_moved": 0,
                "bus_bytes_moved": 0.0})
            a["sites"] += 1
            a["events"] += e.occurrences
            a["total_us"] += e.total_us
            a["bytes_moved"] += e.payload_bytes * e.occurrences
            factor = (e.busbw_gbps / e.algbw_gbps) if e.algbw_gbps else 1.0
            a["bus_bytes_moved"] += e.payload_bytes * e.occurrences * factor
        for a in out.values():
            t = a["total_us"]
            a["algbw_gbps"] = round(a["bytes_moved"] / t / 1e3, 4) if t \
                else 0.0
            a["busbw_gbps"] = round(a["bus_bytes_moved"] / t / 1e3, 4) \
                if t else 0.0
            a["bus_bytes_moved"] = round(a["bus_bytes_moved"], 1)
        return out

    def totals(self) -> dict:
        total_us = sum(e.total_us for e in self.entries)
        bus_bytes = sum(
            e.payload_bytes * e.occurrences
            * ((e.busbw_gbps / e.algbw_gbps) if e.algbw_gbps else 1.0)
            for e in self.entries)
        return {
            "measured_sites": len(self.entries),
            "unmeasured_sites": len(self.unmeasured_instances),
            "unmatched_events": len(self.unmatched_events),
            "events": sum(e.occurrences for e in self.entries),
            "total_us": round(total_us, 3),
            "async_done_us": round(self.async_done_us, 3),
            "busbw_gbps": round(bus_bytes / total_us / 1e3, 4)
            if total_us else 0.0,
        }

    # ---- serialization --------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "schema": LEDGER_SCHEMA_VERSION,
            "axis_sizes": dict(self.axis_sizes),
            "totals": self.totals(),
            "entries": [e.to_dict() for e in self.entries],
            "aggregates": self.aggregates(),
            "unmatched_events": dict(self.unmatched_events),
            "unmeasured_instances": list(self.unmeasured_instances),
            "contract_join": self.contract_join,
        }

    def write(self, run_dir: str) -> str:
        path = os.path.join(run_dir, LEDGER_FILENAME)
        with open(path, "w") as f:
            json.dump(self.to_dict(), f, indent=2, default=str)
            f.write("\n")
        return path


# ------------------------------------------------------------------ build

def build_ledger(event_stats: dict, hlo_text: str,
                 axis_sizes: dict | None = None) -> CollectiveLedger:
    """Join per-instruction trace stats (``collective_event_stats``)
    against the compiled program's collective instructions.

    Payload accounting follows nccl-tests message sizing so the GB/s are
    column-comparable with the reference's NCCL numbers: the message is
    the full logical tensor — an instruction's *output* bytes for
    all_reduce / all_gather / all_to_all / collective_permute, and
    output × group_size for reduce_scatter (whose output is the
    already-scattered shard)."""
    from ..ops.busbench import bus_factor
    from ..ops.hlo import collective_instances

    axis_sizes = {k: int(v) for k, v in (axis_sizes or {}).items()}
    ws = int(math.prod(axis_sizes.values())) if axis_sizes else 1
    instances = {i.name: i for i in collective_instances(hlo_text) if i.name}

    led = CollectiveLedger(axis_sizes=axis_sizes)
    matched = set()
    for name, stats in sorted(event_stats.items()):
        inst = instances.get(name)
        if inst is None:
            if name.split(".")[0].endswith(_DONE_SUFFIXES):
                led.async_done_us += float(stats["total_us"])
            else:
                led.unmatched_events[name] = dict(stats)
            continue
        matched.add(name)
        count = int(stats["count"])
        total_us = float(stats["total_us"])
        mean_us = total_us / count if count else 0.0
        group = len(inst.replica_groups[0]) if inst.replica_groups \
            else max(ws, 1)
        payload = inst.bytes * (group if inst.kind == "reduce_scatter"
                                else 1)
        algbw = payload / mean_us / 1e3 if mean_us else 0.0
        led.entries.append(LedgerEntry(
            name=name, kind=inst.kind, occurrences=count,
            total_us=round(total_us, 3), mean_us=round(mean_us, 4),
            payload_bytes=int(payload),
            dtype=inst.dtypes[0] if inst.dtypes else "",
            group_size=group,
            axis=_axis_for_group(group, axis_sizes),
            algbw_gbps=round(algbw, 4),
            busbw_gbps=round(algbw * bus_factor(inst.kind, group), 4)))
    led.unmeasured_instances = [
        {"name": n, "kind": i.kind, "payload_bytes": i.bytes}
        for n, i in sorted(instances.items()) if n not in matched]
    return led


def ledger_from_trace(trace_dir: str, hlo_text: str,
                      axis_sizes: dict | None = None,
                      session: str | None = None) -> CollectiveLedger | None:
    """Convenience: locate the (owned) trace file under ``trace_dir``
    and build the ledger.  None when no trace exists."""
    from ..utils.trace_analysis import (collective_event_stats,
                                        latest_trace_file)
    tf = latest_trace_file(trace_dir, session=session)
    if tf is None:
        return None
    return build_ledger(collective_event_stats(tf), hlo_text, axis_sizes)


# ------------------------------------------------------------ contract join

def join_contract(ledger: CollectiveLedger, expected: dict,
                  strategy: str = "") -> dict:
    """Measured-side contract verdict: the trace-joined twin of
    ``analysis.check_counts``.  ``expected`` is the serialized verdict's
    expected dict (int / ``"lo..hi"`` / ``"any"`` per kind).  ok iff

      * every program collective was measured (no ``missing_from_trace``),
      * no collective-named trace event fell outside the program
        (no ``unmatched_measured``), and
      * the compiled site count per kind sits in the expected range.

    The verdict is stored back on the ledger (``contract_join``) and
    returned."""
    from ..analysis.contracts import KINDS, parse_expected_spec

    compiled_sites = ledger.sites_by_kind(measured_only=False)
    measured_sites = ledger.sites_by_kind(measured_only=True)
    violations = []
    exp_out = {}
    for kind in KINDS:
        lo, hi = parse_expected_spec(expected.get(kind, 0))
        exp_out[kind] = expected.get(kind, 0)
        got = compiled_sites.get(kind, 0)
        if not lo <= got <= hi:
            hi_s = "inf" if hi == math.inf else int(hi)
            violations.append(
                f"{kind}: {got} compiled sites, contract expects "
                f"{lo}..{hi_s}")
    missing = [r["name"] for r in ledger.unmeasured_instances]
    unmatched = sorted(ledger.unmatched_events)
    for n in missing:
        violations.append(f"expected site never measured in trace: {n}")
    for n in unmatched:
        violations.append(f"measured collective outside the program: {n}")
    verdict = {
        "strategy": strategy,
        "ok": not violations,
        "expected": exp_out,
        "compiled_sites": compiled_sites,
        "measured_sites": measured_sites,
        "missing_from_trace": missing,
        "unmatched_measured": unmatched,
        "violations": violations,
    }
    ledger.contract_join = verdict
    return verdict


# ------------------------------------------------------------- read back

def load_ledger_dict(run_dir: str) -> dict | None:
    """The raw ``collectives.json`` of one run dir, or None."""
    path = os.path.join(run_dir, LEDGER_FILENAME)
    if not os.path.isfile(path):
        return None
    try:
        return json.load(open(path))
    except (OSError, json.JSONDecodeError):
        return None


def check_bandwidth_regressions(cur_aggs: dict, base_aggs: dict,
                                max_drop_pct: float = 20.0,
                                label: str = "", base_label: str = "") \
        -> list[dict]:
    """Diff two ledgers' (kind, bucket, axis) aggregates: one record per
    key present in both, ``regressed`` when busbw dropped more than
    ``max_drop_pct`` percent — the ``--fail-on-bandwidth-regression``
    gate behind ``scripts/report.py``."""
    results = []
    for key, cur in sorted((cur_aggs or {}).items()):
        base = (base_aggs or {}).get(key)
        if not base:
            continue
        a, b = cur.get("busbw_gbps"), base.get("busbw_gbps")
        if not a or not b:
            continue
        delta_pct = (a / b - 1.0) * 100.0
        results.append({
            "run_id": label, "baseline": base_label, "key": key,
            "busbw_gbps": a, "baseline_busbw_gbps": b,
            "delta_pct": round(delta_pct, 2),
            "max_drop_pct": max_drop_pct,
            "regressed": delta_pct < -max_drop_pct,
        })
    return results
