"""Run manifest: the startup facts of one training run.

Captured once before the step loop — everything a later reader needs to
know *what* was run in order to trust the numbers in ``steps.jsonl``:
strategy, the full ``TrainConfig``, mesh geometry, device kind/count,
process topology, jax/jaxlib versions, git sha, and the compile-time HLO
collective counts (``ops.hlo.count_collectives``) of the step function —
the choreography fingerprint that lets the report CLI show "N
all-reduces/step" next to step time.

The startup fields are immutable.  When the run owned a profiler,
``TelemetryRun.finalize`` rewrites the file exactly once to append the
measured-side fields: ``profile_sessions`` (the exact profiler session
dirs this run created — trace ownership, so analysis never grabs a
concurrent run's newer trace), ``ledger`` (the trace-measured
contract verdict from ``telemetry.ledger``, beside the static
``contract`` verdict it mirrors) and ``memory`` (the MemoryVerdict from
``telemetry.memledger`` — the measured-waterline third mark).
"""

from __future__ import annotations

import dataclasses
import datetime
import os
import subprocess
from dataclasses import dataclass, field
from typing import Any

MANIFEST_SCHEMA_VERSION = 1


def _git_sha() -> str | None:
    """Best-effort checkout sha; None outside a git work tree."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=5)
        return out.stdout.strip() or None if out.returncode == 0 else None
    except (OSError, subprocess.SubprocessError):
        return None


def _config_dict(config: Any) -> dict:
    if config is None:
        return {}
    if dataclasses.is_dataclass(config) and not isinstance(config, type):
        return dataclasses.asdict(config)
    if isinstance(config, dict):
        return dict(config)
    return {"repr": repr(config)}


@dataclass
class RunManifest:
    schema: int = MANIFEST_SCHEMA_VERSION
    run_id: str = ""
    strategy: str = ""
    model: str | None = None
    config: dict = field(default_factory=dict)
    mesh_shape: dict = field(default_factory=dict)
    mesh_axes: list = field(default_factory=list)
    device_kind: str = ""
    device_count: int = 0
    local_device_count: int = 0
    process_index: int = 0
    process_count: int = 1
    # os pid of the emitting worker — with extra["rank"] this keys the
    # cross-rank merge (fleet_timeline) back to a concrete process
    pid: int | None = None
    platform: str = ""
    jax_version: str = ""
    jaxlib_version: str | None = None
    git_sha: str | None = None
    started_utc: str = ""
    collective_counts: dict | None = None
    contract: dict | None = None
    # the partition-rule verdict (analysis.rules.rules_manifest_verdict):
    # rule hygiene over the live trees + committed NamedSharding specs
    # vs the rule-derived ones, recorded beside the static contract mark
    rules: dict | None = None
    # restart lineage (resilience.supervisor): attempt index, restart
    # budget, resumed_from_step, the resume contract re-check, and the
    # prior segments' {run_id, start/end_step, status} records —
    # scripts/report.py stitches these into one segmented-run view
    lineage: dict | None = None
    # appended at finalize when the run owned a profiler (see module
    # docstring): session dirs this run's traces live in, and the
    # measured collective-ledger verdict beside the static contract one
    profile_sessions: list | None = None
    ledger: dict | None = None
    # the memory ledger's MemoryVerdict (telemetry.memledger): measured
    # allocator peak joined to the compiled memory_analysis() waterline
    # and, where the driver passed one, the planner prediction
    memory: dict | None = None
    extra: dict = field(default_factory=dict)

    @classmethod
    def capture(cls, strategy: str, *, run_id: str = "",
                config: Any = None, mesh=None, model: str | None = None,
                collective_counts: dict | None = None,
                contract: dict | None = None,
                rules: dict | None = None,
                lineage: dict | None = None,
                extra: dict | None = None) -> "RunManifest":
        """Snapshot the environment at step 0.  ``mesh`` is a
        ``jax.sharding.Mesh`` (or None for meshless scripts);
        ``collective_counts`` is the ``count_collectives`` dict the
        scripts already compute for their startup print; ``contract``
        is the ``analysis.ContractVerdict.to_dict()`` of checking those
        counts against the strategy's choreography contract."""
        import jax
        dev = jax.devices()[0]
        jaxlib_version = None
        try:
            import jaxlib
            jaxlib_version = getattr(jaxlib, "__version__", None)
        except ImportError:
            pass
        return cls(
            run_id=run_id,
            strategy=strategy,
            model=model,
            config=_config_dict(config),
            mesh_shape=dict(mesh.shape) if mesh is not None else {},
            mesh_axes=list(mesh.axis_names) if mesh is not None else [],
            device_kind=getattr(dev, "device_kind", str(dev)),
            device_count=jax.device_count(),
            local_device_count=len(jax.local_devices()),
            process_index=jax.process_index(),
            process_count=jax.process_count(),
            pid=os.getpid(),
            platform=dev.platform,
            jax_version=jax.__version__,
            jaxlib_version=jaxlib_version,
            git_sha=_git_sha(),
            started_utc=datetime.datetime.now(
                datetime.timezone.utc).isoformat(timespec="seconds"),
            collective_counts=collective_counts,
            contract=contract,
            rules=rules,
            lineage=dict(lineage) if lineage else None,
            extra=dict(extra or {}),
        )

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)
