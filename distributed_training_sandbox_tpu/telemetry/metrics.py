"""Live metrics: a tiny in-process registry with a Prometheus text
endpoint.

The post-hoc artifacts (steps.jsonl, summary.json) answer "what
happened"; this module answers "what is happening".  A
:class:`MetricsRegistry` holds counters, gauges and histograms fed by
the hot paths (pump syncs, prefetch waits, admission decisions,
checkpoint saves, heartbeats), and :class:`MetricsServer` exposes them
on a stdlib HTTP endpoint in Prometheus text exposition format — no
third-party client library, no background scrape agent.

Conventions:

  * metric names are static strings at the call site (enforced by the
    ``span-name-not-static`` pitfall lint — dynamic dimensions go in
    labels, never in the name);
  * counters end in ``_total``, histograms in a unit suffix
    (``_seconds``);
  * every feed site is ``None``-tolerant via :func:`maybe_inc` /
    :func:`maybe_set` / :func:`maybe_observe`, mirroring
    ``spans.maybe_span`` — instrumentation never becomes a hard
    dependency of the thing it observes.

The registry is thread-safe (prefetch producer threads, checkpoint
writeback threads and the HTTP server all touch it concurrently) and
deliberately unbounded-cardinality-hostile: label values are
stringified and the lint keeps names static, so the series count is
bounded by code, not by data.
"""

from __future__ import annotations

import http.server
import json
import threading
import time

__all__ = [
    "MetricsRegistry", "MetricsServer",
    "maybe_inc", "maybe_set", "maybe_observe",
]

# Default histogram buckets (seconds): spans the range from a fast
# host sync (~100us) to a slow checkpoint save / prefill (~10s).
DEFAULT_BUCKETS = (0.0001, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
                   1.0, 5.0, 10.0)


def _label_key(labels: dict) -> tuple:
    """Canonical hashable key for a label set (values stringified)."""
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _fmt_labels(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    # Prometheus accepts float repr; render integral values as ints so
    # counter output is stable and diff-friendly.
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


class MetricsRegistry:
    """Thread-safe counters / gauges / histograms with Prometheus text
    rendering and JSON snapshots.

    Names should be bare (``pump_host_sync_total``); a ``namespace``
    prefix (default ``dts``) is applied at render/snapshot time so feed
    sites stay short."""

    def __init__(self, namespace: str = "dts"):
        self.namespace = namespace
        self._lock = threading.Lock()
        # name -> {label_key -> value}
        self._counters: dict[str, dict[tuple, float]] = {}
        self._gauges: dict[str, dict[tuple, float]] = {}
        # name -> {label_key -> {"buckets": [counts], "sum": s, "count": n}}
        self._hists: dict[str, dict[tuple, dict]] = {}
        self._hist_buckets: dict[str, tuple] = {}

    # -- feeds ------------------------------------------------------

    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            series = self._counters.setdefault(name, {})
            series[key] = series.get(key, 0.0) + float(value)

    def set(self, name: str, value: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._gauges.setdefault(name, {})[key] = float(value)

    def observe(self, name: str, value: float,
                buckets: tuple = DEFAULT_BUCKETS, **labels) -> None:
        key = _label_key(labels)
        v = float(value)
        with self._lock:
            bks = self._hist_buckets.setdefault(name, tuple(buckets))
            series = self._hists.setdefault(name, {})
            h = series.setdefault(
                key, {"buckets": [0] * len(bks), "sum": 0.0, "count": 0})
            for i, le in enumerate(bks):
                if v <= le:
                    h["buckets"][i] += 1
            h["sum"] += v
            h["count"] += 1

    # -- reads ------------------------------------------------------

    def get(self, name: str, **labels) -> float | None:
        """Current value of a counter or gauge series (None if unseen)."""
        key = _label_key(labels)
        with self._lock:
            for table in (self._counters, self._gauges):
                if name in table and key in table[name]:
                    return table[name][key]
        return None

    def counter_total(self, name: str) -> float:
        """Sum of a counter across all label sets (0.0 if unseen)."""
        with self._lock:
            return float(sum(self._counters.get(name, {}).values()))

    def __bool__(self) -> bool:
        with self._lock:
            return bool(self._counters or self._gauges or self._hists)

    def snapshot(self) -> dict:
        """Flat JSON-ready snapshot: ``{"counters": {...}, "gauges":
        {...}, "histograms": {...}}`` with ``name{k="v"}`` keys."""
        ns = self.namespace + "_" if self.namespace else ""
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        with self._lock:
            for name, series in self._counters.items():
                for key, v in series.items():
                    out["counters"][ns + name + _fmt_labels(key)] = v
            for name, series in self._gauges.items():
                for key, v in series.items():
                    out["gauges"][ns + name + _fmt_labels(key)] = v
            for name, series in self._hists.items():
                for key, h in series.items():
                    out["histograms"][ns + name + _fmt_labels(key)] = {
                        "count": h["count"], "sum": h["sum"]}
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition format (0.0.4)."""
        ns = self.namespace + "_" if self.namespace else ""
        lines: list[str] = []
        with self._lock:
            for name in sorted(self._counters):
                full = ns + name
                lines.append(f"# TYPE {full} counter")
                for key in sorted(self._counters[name]):
                    lines.append(f"{full}{_fmt_labels(key)} "
                                 f"{_fmt_value(self._counters[name][key])}")
            for name in sorted(self._gauges):
                full = ns + name
                lines.append(f"# TYPE {full} gauge")
                for key in sorted(self._gauges[name]):
                    lines.append(f"{full}{_fmt_labels(key)} "
                                 f"{_fmt_value(self._gauges[name][key])}")
            for name in sorted(self._hists):
                full = ns + name
                bks = self._hist_buckets[name]
                lines.append(f"# TYPE {full} histogram")
                for key in sorted(self._hists[name]):
                    h = self._hists[name][key]
                    base = dict(key)
                    cum = 0
                    for le, n in zip(bks, h["buckets"]):
                        cum = n  # buckets are already cumulative per-le
                        lk = _fmt_labels(_label_key({**base, "le": le}))
                        lines.append(f"{full}_bucket{lk} {cum}")
                    lk = _fmt_labels(_label_key({**base, "le": "+Inf"}))
                    lines.append(f"{full}_bucket{lk} {h['count']}")
                    lines.append(f"{full}_sum{_fmt_labels(key)} "
                                 f"{_fmt_value(h['sum'])}")
                    lines.append(f"{full}_count{_fmt_labels(key)} "
                                 f"{h['count']}")
        return "\n".join(lines) + ("\n" if lines else "")

    def write_snapshot(self, path: str) -> None:
        """Append one timestamped snapshot line to a metrics.jsonl."""
        line = json.dumps({"ts": time.time(), **self.snapshot()},
                          sort_keys=True)
        with open(path, "a") as f:
            f.write(line + "\n")


class _Handler(http.server.BaseHTTPRequestHandler):
    # Set per-server via a subclass attribute in MetricsServer.
    registry: MetricsRegistry | None = None

    def do_GET(self):  # noqa: N802 (stdlib naming)
        if self.path.rstrip("/") not in ("", "/metrics"):
            self.send_error(404)
            return
        body = self.registry.render_prometheus().encode()
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # silence per-request stderr spam
        pass


class MetricsServer:
    """Prometheus scrape endpoint on a daemon thread.

    ``port=0`` binds an ephemeral port; read it back from ``.port``
    (this is how tests scrape a live run without port collisions)."""

    def __init__(self, registry: MetricsRegistry, port: int = 0,
                 host: str = "127.0.0.1"):
        handler = type("_BoundHandler", (_Handler,), {"registry": registry})
        self._httpd = http.server.ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self.host = host
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="dts-metrics",
            daemon=True)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def start(self) -> "MetricsServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


# -- None-tolerant feed helpers (mirror spans.maybe_span) ------------
# forwarders: the caller's literal passes through (lint checks THEM)

def maybe_inc(metrics: MetricsRegistry | None, name: str,
              value: float = 1.0, **labels) -> None:
    if metrics is not None:
        metrics.inc(name, value, **labels)   # span-ok


def maybe_set(metrics: MetricsRegistry | None, name: str,
              value: float, **labels) -> None:
    if metrics is not None:
        metrics.set(name, value, **labels)   # span-ok


def maybe_observe(metrics: MetricsRegistry | None, name: str,
                  value: float, **labels) -> None:
    if metrics is not None:
        metrics.observe(name, value, **labels)   # span-ok
