"""TelemetryRun: the one object a training script holds.

Glues the pieces together around the step loop:

  * captures and writes the :class:`RunManifest` at entry;
  * appends one ``schema.step_event`` per optimizer step — every rank
    emits, rank > 0 under a ``-r<rank>`` run-id suffix so
    ``scripts/fleet_timeline.py`` can merge a launch group — timing
    steps host-side and lifting rates from the ``PerformanceTracker``
    metrics dict the scripts already compute;
  * owns the live :class:`~.metrics.MetricsRegistry` (fed by the pump,
    prefetcher, checkpointer, batcher, router and heartbeats) and, when
    ``--metrics-port`` is set, its Prometheus scrape endpoint plus
    periodic ``metrics.jsonl`` snapshots;
  * owns the ``Profiler`` lifecycle — ``step()`` advances it and
    ``__exit__`` stops it on *every* path, so an exception mid-loop
    still flushes the in-flight ``jax.profiler`` trace (the reference
    scripts only called ``prof.stop()`` on the happy path and lost the
    trace on crash);
  * owns the host-phase ``SpanStream`` (``spans.jsonl``) the runtime
    pieces (pump, prefetcher, checkpointer, serving engine) record
    their wait/dispatch spans into;
  * writes ``summary.json`` at exit — aggregates plus, when profiling
    was on, the ``trace_analysis.split_from_trace`` comm/compute split
    of the profiler session this run *owns* (not "newest trace by
    mtime" — a concurrent run must not be misattributed) and the trace
    dir; a crash writes status="crashed" with the error;
  * when the script also attached its compiled HLO (:meth:`attach_hlo`),
    builds the :mod:`telemetry.ledger` CollectiveLedger from the owned
    trace — per-collective payloads and bus-GB/s in
    ``collectives.json``, with the measured contract verdict appended
    to ``manifest.json`` beside the static one;
  * when :meth:`attach_step_hlo` also captured the compiled step's
    ``memory_analysis()``, builds the :mod:`telemetry.memledger`
    MemoryLedger — attributed categories + the phase-spanned allocator
    timeline in ``memory.json``, with the MemoryVerdict stamped into
    ``manifest.json`` as the third mark beside the contract and
    collective-ledger verdicts.

Usage (the shape every scripts/ entrypoint now follows)::

    with TelemetryRun("fsdp", config=cfg, mesh=mesh, model=args.model,
                      collective_counts=counts, profiler=prof) as telem:
        for i in range(cfg.num_steps):
            ...
            metrics = tracker.step(tokens, loss=loss)
            telem.step(loss=loss, tokens=tokens, tracker_metrics=metrics)
    # telemetry + profiler both finalized here, crash or not
"""

from __future__ import annotations

import os
import statistics
import time

from ..utils.config import build_run_id, default_results_dir
from .manifest import RunManifest
from .schema import step_event
from .writer import MetricsWriter


class TelemetryRun:
    def __init__(self, strategy: str, *, config=None, mesh=None,
                 model: str | None = None,
                 collective_counts: dict | None = None,
                 contract: dict | None = None,
                 rules: dict | None = None,
                 lineage: dict | None = None,
                 extra: dict | None = None,
                 results_dir: str | None = None,
                 run_name: str | None = None,
                 profiler=None, enabled: bool | None = None,
                 metrics_port: int | None = None,
                 metrics_snapshot_s: float = 10.0):
        import jax
        self.strategy = strategy
        self.config = config
        self.mesh = mesh
        self.model = model
        self.collective_counts = collective_counts
        self.contract = contract
        self.rules = rules
        self.lineage = lineage
        self.extra = extra
        self.profiler = profiler
        if results_dir is None:
            results_dir = getattr(config, "results_dir", None) \
                or default_results_dir()
        if run_name is None:
            run_name = getattr(config, "run_name", None)
        want = getattr(config, "telemetry", True) if enabled is None \
            else enabled
        # every rank emits its own artifacts (rank > 0 under a
        # ``-r<rank>`` run-id suffix) so scripts/fleet_timeline.py can
        # merge a launch group; DTS_PROCESS_ID wins over
        # jax.process_index() so launcher-spawned workers that never
        # initialize jax.distributed still stamp their true rank
        env_rank = os.environ.get("DTS_PROCESS_ID")
        self.rank = int(env_rank) if env_rank else jax.process_index()
        self.enabled = bool(want)
        self.results_dir = results_dir
        self.run_id = self._unique_run_id(results_dir, strategy, run_name,
                                          rank=self.rank)
        self.run_dir = os.path.join(results_dir, self.run_id) \
            if self.enabled else None
        # live metrics: registry always present while enabled (feed
        # sites are None-guarded), HTTP endpoint only on request
        if metrics_port is None:
            metrics_port = getattr(config, "metrics_port", None)
        self._metrics_port = metrics_port
        self.metrics_snapshot_s = float(metrics_snapshot_s)
        self.metrics = None
        self.metrics_server = None
        self._t_metrics_snapshot: float | None = None
        self._metrics_snapshots = 0
        if self.enabled:
            from .metrics import MetricsRegistry
            self.metrics = MetricsRegistry()
        self.writer: MetricsWriter | None = None
        self.manifest: RunManifest | None = None
        self._step_idx = 0
        self._losses: list[float] = []
        self._step_times: list[float] = []
        self._last_tracker_metrics: dict | None = None
        self._tokens_total = 0
        self._t_prev: float | None = None
        self._finalized = False
        # deferred (device-array) losses: events buffered here until the
        # pump's next sync point resolves them (see flush())
        self._deferred: list[tuple[dict, object]] = []
        # set by StepPump.close(); lands in summary.json
        self.host_sync_count: int | None = None
        self.host_sync_breakdown: dict | None = None
        # host-phase span stream (spans.jsonl), created at start();
        # None when telemetry is off — call sites guard via maybe_span
        self.spans = None
        # compiled HLO of the step program (attach_hlo), joined against
        # the owned trace at finalize to build the collective ledger
        self._hlo_text: str | None = None
        # compiled-step memory accounting (attach_step_hlo): the
        # memory_analysis() breakdown, eager tree-walk bytes per named
        # arg category (computed BEFORE donation invalidates the
        # buffers), per-path param attribution, and the driver's
        # planner/serving prediction — joined at finalize into the
        # memory ledger (memory.json)
        self._memory_analysis: dict | None = None
        self._mem_trees_bytes: dict | None = None
        self._mem_param_paths: dict | None = None
        self._mem_prediction: dict | None = None

    @staticmethod
    def _unique_run_id(results_dir: str, strategy: str,
                       run_name: str | None, rank: int = 0) -> str:
        label = strategy if not run_name else f"{strategy}-{run_name}"
        rid = build_run_id(label)
        if rank:
            # rank-suffixed so N ranks of one launch group land as N
            # sibling run dirs (merged by scripts/fleet_timeline.py)
            rid = f"{rid}-r{rank}"
        # second-resolution timestamps collide when two runs start in the
        # same second (the test suite does exactly that)
        n, base = 2, rid
        while os.path.exists(os.path.join(results_dir, rid)):
            rid = f"{base}-{n}"
            n += 1
        return rid

    # ---- lifecycle ------------------------------------------------------
    def start(self) -> "TelemetryRun":
        if self.enabled:
            extra = dict(self.extra or {})
            extra.setdefault("rank", self.rank)
            group = os.environ.get("DTS_LAUNCH_GROUP")
            if group:
                # launcher-stamped group id: fleet_timeline groups the
                # per-rank run dirs of one `dts-launch run` by this key
                extra.setdefault("launch_group", group)
            coord = os.environ.get("DTS_COORDINATOR")
            if coord:
                # the launcher-chosen coordinator address:port — the
                # fleet-timeline join can tell two groups apart even
                # when their launch ids collide, and a port-rotation
                # retry is visible as a changed port across attempts
                extra.setdefault("coordinator", coord)
            self.manifest = RunManifest.capture(
                self.strategy, run_id=self.run_id, config=self.config,
                mesh=self.mesh, model=self.model,
                collective_counts=self.collective_counts,
                contract=self.contract,
                rules=self.rules,
                lineage=self.lineage,
                extra=extra)
            self.writer = MetricsWriter(self.run_dir)
            self.writer.write_manifest(self.manifest)
            from .spans import SpanStream
            self.spans = SpanStream(self.run_dir)
            # phase-spanned allocator timeline: every host span the
            # stream appends also samples the shared device-memory
            # sampler under that span's phase (memledger.PHASES)
            from .memledger import get_sampler
            self.spans.sampler = get_sampler()
            if self._metrics_port is not None:
                from .metrics import MetricsServer
                self.metrics_server = MetricsServer(
                    self.metrics, port=int(self._metrics_port)).start()
                self._t_metrics_snapshot = time.perf_counter()
        self._t_prev = time.perf_counter()
        return self

    def attach_hlo(self, compiled_text: str) -> None:
        """Hand over the step program's ``compile().as_text()`` so
        finalize can join the profiler trace against it (the collective
        ledger needs instruction names + payload shapes).  Scripts call
        this only when profiling is on — lowering+compiling purely for
        the text would otherwise double compile cost."""
        self._hlo_text = compiled_text

    def attach_step_hlo(self, jitted, *args, trees=None,
                        prediction=None) -> None:
        """Driver-facing form of :meth:`attach_hlo`: AOT-lower ``jitted``
        at ``args`` and attach the compiled text.  ``args`` MUST be the
        exact arrays the hot loop passes (same shapes, dtypes AND
        shardings) — a differently-sharded example would compile a
        different program whose instruction names don't match the traced
        one, and the ledger join would report every site unmeasured.
        No-op unless this run owns an *enabled* profiler (no trace, no
        join — don't pay the extra compile); never raises.

        The same compile also feeds the memory ledger: its
        ``memory_analysis()`` breakdown is captured, and ``trees`` — a
        ``{category: pytree}`` dict of the named argument state
        (defaulting to ``{params, opt_state, batch}`` from the first
        three positional args, the universal train-step signature) — is
        tree-walked into per-category bytes EAGERLY, because donation
        invalidates these buffers the moment the hot loop runs.
        ``prediction`` (a WaterlinePrediction-shaped dict, optional)
        records the driver's analytic/serving waterline for the
        measured-vs-predicted join at finalize."""
        prof = self.profiler
        if not self.enabled or self._hlo_text is not None \
                or prof is None or not getattr(prof, "enabled", False):
            return
        try:
            compiled = jitted.lower(*args).compile()
            self.attach_hlo(compiled.as_text())
        except Exception as e:   # best-effort: telemetry must not crash
            print(f"[telemetry] WARNING: could not attach compiled HLO "
                  f"for the collective ledger: {type(e).__name__}: {e}")
            return
        try:
            ma = compiled.memory_analysis()
            if ma is not None:
                self._memory_analysis = {
                    "argument_bytes": int(ma.argument_size_in_bytes),
                    "output_bytes": int(ma.output_size_in_bytes),
                    "temp_bytes": int(ma.temp_size_in_bytes),
                    "alias_bytes": int(ma.alias_size_in_bytes),
                }
            if trees is None and len(args) >= 3:
                trees = {"params": args[0], "opt_state": args[1],
                         "batch": args[2]}
            if trees:
                from ..utils.memory import tree_size_bytes
                from .memledger import param_path_bytes
                self._mem_trees_bytes = {
                    k: tree_size_bytes(v) for k, v in trees.items()}
                if "params" in trees:
                    self._mem_param_paths = param_path_bytes(
                        trees["params"])
            if prediction is not None:
                self._mem_prediction = prediction.to_dict() \
                    if hasattr(prediction, "to_dict") else dict(prediction)
        except Exception as e:   # best-effort: telemetry must not crash
            print(f"[telemetry] WARNING: could not attribute step memory "
                  f"for the memory ledger: {type(e).__name__}: {e}")

    def __enter__(self) -> "TelemetryRun":
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        # profiler first: flush the in-flight trace whatever happened
        if self.profiler is not None:
            self.profiler.stop()
        if exc_type is not None:
            self.finalize(status="crashed",
                          error=f"{exc_type.__name__}: {exc}")
        else:
            self.finalize()
        return False

    # ---- per-step -------------------------------------------------------
    def step(self, *, loss=None, tokens: int | None = None,
             tracker_metrics: dict | None = None, **extra) -> None:
        """Record one optimizer step.  Also advances the owned profiler,
        so the loop needs no separate ``prof.step()`` call.

        ``loss`` may be a host float (written through immediately, the
        classic path) or a *device array* still in flight — then the
        event is buffered with a null loss and resolved at the next
        :meth:`flush` (the async pump's sync points), so the JSONL
        schema is unchanged and rows stay in step order."""
        now = time.perf_counter()
        dt = now - self._t_prev if self._t_prev is not None else None
        self._t_prev = now
        if self.profiler is not None:
            self.profiler.step()
        tm = tracker_metrics or {}
        step_time = tm.get("last_step_time_s") or dt
        extra.setdefault("rank", self.rank)
        if self.metrics is not None:
            self.metrics.inc("steps_total")
            if tokens:
                self.metrics.inc("tokens_total", int(tokens))
            if step_time is not None:
                self.metrics.set("last_step_time_s", float(step_time))
            self._maybe_snapshot_metrics(now)
        deferred = loss is not None and hasattr(loss, "block_until_ready")
        if step_time is not None:
            self._step_times.append(float(step_time))
        if tokens:
            self._tokens_total += int(tokens)
        if tm:
            self._last_tracker_metrics = tm
        idx = self._step_idx
        self._step_idx += 1
        if deferred:
            ev = step_event(idx, loss=None, tokens=tokens,
                            step_time_s=step_time,
                            tracker_metrics=tracker_metrics, **extra)
            self._deferred.append((ev, loss))
            return
        if self._deferred:       # keep steps.jsonl in step order
            self.flush()
        if loss is not None:
            self._losses.append(float(loss))
        if self.writer is not None:
            self.writer.append_step(step_event(
                idx, loss=loss, tokens=tokens, step_time_s=step_time,
                tracker_metrics=tracker_metrics, **extra))

    def _maybe_snapshot_metrics(self, now: float) -> None:
        """Append a timestamped line to ``metrics.jsonl`` every
        ``metrics_snapshot_s`` while the endpoint is live (snapshots and
        endpoint are one feature: runs that never asked for live
        metrics keep their exact artifact set)."""
        if self.metrics_server is None or self.run_dir is None \
                or self._t_metrics_snapshot is None:
            return
        if now - self._t_metrics_snapshot < self.metrics_snapshot_s:
            return
        self._t_metrics_snapshot = now
        try:
            self.metrics.write_snapshot(
                os.path.join(self.run_dir, "metrics.jsonl"))
            self._metrics_snapshots += 1
        except OSError:
            pass

    def flush(self, up_to: int | None = None) -> None:
        """Resolve buffered deferred-loss events (oldest first; all of
        them, or the first ``up_to``) and hand them to the writer.  The
        caller — the pump at a sync point, or finalize — is responsible
        for the losses being (near-)ready; resolution of a poisoned
        array degrades to a null loss rather than raising."""
        n = len(self._deferred) if up_to is None \
            else min(up_to, len(self._deferred))
        for _ in range(n):
            ev, arr = self._deferred.pop(0)
            try:
                from ..utils.mesh import local_scalar
                lf = local_scalar(arr)
            except Exception:   # crash path: keep the original exception
                lf = None
            if lf is not None:
                ev["loss"] = lf
                self._losses.append(lf)
            if self.writer is not None:
                self.writer.append_step(ev)
        if self.writer is not None:
            self.writer.flush()

    # ---- end-of-run -----------------------------------------------------
    def _aggregates(self) -> dict:
        out: dict = {
            "steps_recorded": self._step_idx,
            "total_tokens": self._tokens_total,
        }
        if self._losses:
            out["first_loss"] = self._losses[0]
            out["final_loss"] = self._losses[-1]
            out["avg_loss"] = sum(self._losses) / len(self._losses)
        if self._step_times:
            # median over the post-compile tail: step 0 carries the jit
            times = self._step_times[1:] or self._step_times
            out["step_time_ms"] = statistics.median(times) * 1e3
            out["step_time_ms_mean"] = sum(times) / len(times) * 1e3
        tm = self._last_tracker_metrics or {}
        for k in ("tokens_per_second", "steps_per_second",
                  "tflops_per_device", "peak_memory_gb"):
            if tm.get(k) is not None:
                out[k] = tm[k]
        return out

    def finalize(self, status: str = "completed", error: str | None = None,
                 **extra) -> dict | None:
        """Write ``summary.json``.  Idempotent: a crash path overwrites a
        not-yet-written summary only; explicit double calls are no-ops."""
        if self._finalized:
            return None
        self._finalized = True
        try:
            self.flush()     # resolve any still-deferred losses
        except Exception:
            pass
        if not self.enabled or self.writer is None:
            return None
        summary: dict = {
            "run_id": self.run_id,
            "strategy": self.strategy,
            "model": self.model,
            "status": status,
        }
        if error:
            summary["error"] = error
        cfg = self.manifest.config if self.manifest else {}
        for k in ("sequence_length", "batch_size", "num_steps",
                  "precision", "seed"):
            if k in cfg:
                summary[k] = cfg[k]
        summary.update(self._aggregates())
        if self.host_sync_count is not None:
            # the pump's instrumented blocking events (policy barriers +
            # backpressure waits) — the async-dispatch acceptance metric
            summary["host_sync_count"] = self.host_sync_count
            summary["host_sync_breakdown"] = self.host_sync_breakdown
        summary.update(extra)
        # post-run profiling hook: comm/compute split + collective
        # ledger from the trace session the owned Profiler just flushed
        # (falling back to newest-under-trace_dir only when the profiler
        # predates session ownership)
        prof = self.profiler
        if prof is not None and getattr(prof, "enabled", False):
            summary["trace_dir"] = prof.trace_dir
            owned = list(getattr(prof, "owned_sessions", None) or [])
            session = owned[-1] if owned else None
            if owned:
                summary["profile_sessions"] = owned
            try:
                from ..utils.trace_analysis import split_from_trace
                sp = split_from_trace(prof.trace_dir, session=session)
            except Exception:   # trace parsing must never fail the run
                sp = None
            if sp is not None:
                summary["comm_split"] = {
                    "comm_us": sp.comm_us,
                    "compute_us": sp.compute_us,
                    "other_us": sp.other_us,
                    "comm_fraction": sp.comm_fraction,
                    "overlap_us": sp.overlap_us,
                    "overlap_fraction": sp.overlap_fraction,
                    "trace_file": sp.trace_file,
                }
            ledger_verdict = None
            if self._hlo_text is not None:
                try:
                    ledger_verdict = self._build_ledger(session)
                except Exception:   # ledger must never fail the run
                    ledger_verdict = None
            if ledger_verdict is not None:
                summary["ledger"] = ledger_verdict
            mem_verdict = None
            if self._memory_analysis is not None:
                try:
                    mem_verdict = self._build_memory()
                except Exception:   # memory ledger must never fail the run
                    mem_verdict = None
            if mem_verdict is not None:
                summary["memory"] = mem_verdict
            if self.manifest is not None and (owned or ledger_verdict
                                              or mem_verdict):
                # the one sanctioned manifest rewrite (see
                # telemetry.manifest): append the measured-side facts
                self.manifest.profile_sessions = owned or None
                self.manifest.ledger = ledger_verdict
                self.manifest.memory = mem_verdict
                self.writer.write_manifest(self.manifest)
        if self.spans is not None:
            self.spans.close()
            if self.spans.spans_written:
                summary["spans_recorded"] = self.spans.spans_written
        if self.metrics is not None and self.metrics:
            # final counter values — the live endpoint's last scrape and
            # this block must agree (pinned by test_obsplane)
            summary["metrics"] = self.metrics.snapshot()
        if self.metrics_server is not None:
            try:
                self.metrics.write_snapshot(
                    os.path.join(self.run_dir, "metrics.jsonl"))
            except OSError:
                pass
            self.metrics_server.stop()
            self.metrics_server = None
        self.writer.write_summary(summary)
        self.writer.close()
        return summary

    def _build_memory(self) -> dict | None:
        """Build + file the memory ledger (``memory.json``); returns the
        MemoryVerdict block stamped into summary/manifest beside the
        contract and collective-ledger verdicts, or None when the attach
        captured no ``memory_analysis()``."""
        if self._memory_analysis is None:
            return None
        from .memledger import (MEMORY_FILENAME, build_memory_ledger,
                                get_sampler, join_prediction)
        capacity = None
        cfg = self.manifest.config if self.manifest else {}
        if isinstance(cfg, dict) and cfg.get("hbm_budget_gb"):
            capacity = float(cfg["hbm_budget_gb"])
        led = build_memory_ledger(
            self._memory_analysis, self._mem_trees_bytes,
            self._hlo_text or "", sampler=get_sampler(),
            param_paths=self._mem_param_paths, capacity_gb=capacity)
        verdict = join_prediction(led, self._mem_prediction,
                                  strategy=self.strategy)
        self.writer.write_json(MEMORY_FILENAME, led.to_dict())
        return verdict

    def _build_ledger(self, session: str | None) -> dict | None:
        """Build + file the collective ledger; returns the compact
        verdict block that lands in summary/manifest, or None when no
        trace was found."""
        from .ledger import join_contract, ledger_from_trace
        axis_sizes = dict(self.mesh.shape) if self.mesh is not None \
            else dict((self.manifest.mesh_shape or {})
                      if self.manifest else {})
        led = ledger_from_trace(self.profiler.trace_dir, self._hlo_text,
                                axis_sizes, session=session)
        if led is None:
            return None
        join = None
        if self.contract and isinstance(self.contract, dict) \
                and self.contract.get("expected"):
            join = join_contract(led, self.contract["expected"],
                                 strategy=self.strategy)
        self.writer.write_json("collectives.json", led.to_dict())
        totals = led.totals()
        out = {
            "measured_sites": totals["measured_sites"],
            "unmeasured_sites": totals["unmeasured_sites"],
            "unmatched_events": totals["unmatched_events"],
            "busbw_gbps": totals["busbw_gbps"],
        }
        if join is not None:
            out["ok"] = join["ok"]
            out["violations"] = join["violations"]
        return out
