"""TelemetryRun: the one object a training script holds.

Glues the pieces together around the step loop:

  * captures and writes the :class:`RunManifest` at entry;
  * appends one ``schema.step_event`` per optimizer step (rank-0 only),
    timing steps host-side and lifting rates from the
    ``PerformanceTracker`` metrics dict the scripts already compute;
  * owns the ``Profiler`` lifecycle — ``step()`` advances it and
    ``__exit__`` stops it on *every* path, so an exception mid-loop
    still flushes the in-flight ``jax.profiler`` trace (the reference
    scripts only called ``prof.stop()`` on the happy path and lost the
    trace on crash);
  * writes ``summary.json`` at exit — aggregates plus, when profiling
    was on, the ``trace_analysis.split_from_trace`` comm/compute split
    and the trace dir; a crash writes status="crashed" with the error.

Usage (the shape every scripts/ entrypoint now follows)::

    with TelemetryRun("fsdp", config=cfg, mesh=mesh, model=args.model,
                      collective_counts=counts, profiler=prof) as telem:
        for i in range(cfg.num_steps):
            ...
            metrics = tracker.step(tokens, loss=loss)
            telem.step(loss=loss, tokens=tokens, tracker_metrics=metrics)
    # telemetry + profiler both finalized here, crash or not
"""

from __future__ import annotations

import os
import statistics
import time

from ..utils.config import build_run_id, default_results_dir
from .manifest import RunManifest
from .schema import step_event
from .writer import MetricsWriter


class TelemetryRun:
    def __init__(self, strategy: str, *, config=None, mesh=None,
                 model: str | None = None,
                 collective_counts: dict | None = None,
                 contract: dict | None = None,
                 lineage: dict | None = None,
                 extra: dict | None = None,
                 results_dir: str | None = None,
                 run_name: str | None = None,
                 profiler=None, enabled: bool | None = None):
        import jax
        self.strategy = strategy
        self.config = config
        self.mesh = mesh
        self.model = model
        self.collective_counts = collective_counts
        self.contract = contract
        self.lineage = lineage
        self.extra = extra
        self.profiler = profiler
        if results_dir is None:
            results_dir = getattr(config, "results_dir", None) \
                or default_results_dir()
        if run_name is None:
            run_name = getattr(config, "run_name", None)
        want = getattr(config, "telemetry", True) if enabled is None \
            else enabled
        # telemetry artifacts are rank-0-only; profiler ownership is not
        self.enabled = bool(want) and jax.process_index() == 0
        self.results_dir = results_dir
        self.run_id = self._unique_run_id(results_dir, strategy, run_name)
        self.run_dir = os.path.join(results_dir, self.run_id) \
            if self.enabled else None
        self.writer: MetricsWriter | None = None
        self.manifest: RunManifest | None = None
        self._step_idx = 0
        self._losses: list[float] = []
        self._step_times: list[float] = []
        self._last_tracker_metrics: dict | None = None
        self._tokens_total = 0
        self._t_prev: float | None = None
        self._finalized = False
        # deferred (device-array) losses: events buffered here until the
        # pump's next sync point resolves them (see flush())
        self._deferred: list[tuple[dict, object]] = []
        # set by StepPump.close(); lands in summary.json
        self.host_sync_count: int | None = None
        self.host_sync_breakdown: dict | None = None

    @staticmethod
    def _unique_run_id(results_dir: str, strategy: str,
                       run_name: str | None) -> str:
        label = strategy if not run_name else f"{strategy}-{run_name}"
        rid = build_run_id(label)
        # second-resolution timestamps collide when two runs start in the
        # same second (the test suite does exactly that)
        n, base = 2, rid
        while os.path.exists(os.path.join(results_dir, rid)):
            rid = f"{base}-{n}"
            n += 1
        return rid

    # ---- lifecycle ------------------------------------------------------
    def start(self) -> "TelemetryRun":
        if self.enabled:
            self.manifest = RunManifest.capture(
                self.strategy, run_id=self.run_id, config=self.config,
                mesh=self.mesh, model=self.model,
                collective_counts=self.collective_counts,
                contract=self.contract,
                lineage=self.lineage,
                extra=self.extra)
            self.writer = MetricsWriter(self.run_dir)
            self.writer.write_manifest(self.manifest)
        self._t_prev = time.perf_counter()
        return self

    def __enter__(self) -> "TelemetryRun":
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        # profiler first: flush the in-flight trace whatever happened
        if self.profiler is not None:
            self.profiler.stop()
        if exc_type is not None:
            self.finalize(status="crashed",
                          error=f"{exc_type.__name__}: {exc}")
        else:
            self.finalize()
        return False

    # ---- per-step -------------------------------------------------------
    def step(self, *, loss=None, tokens: int | None = None,
             tracker_metrics: dict | None = None, **extra) -> None:
        """Record one optimizer step.  Also advances the owned profiler,
        so the loop needs no separate ``prof.step()`` call.

        ``loss`` may be a host float (written through immediately, the
        classic path) or a *device array* still in flight — then the
        event is buffered with a null loss and resolved at the next
        :meth:`flush` (the async pump's sync points), so the JSONL
        schema is unchanged and rows stay in step order."""
        now = time.perf_counter()
        dt = now - self._t_prev if self._t_prev is not None else None
        self._t_prev = now
        if self.profiler is not None:
            self.profiler.step()
        tm = tracker_metrics or {}
        step_time = tm.get("last_step_time_s") or dt
        deferred = loss is not None and hasattr(loss, "block_until_ready")
        if step_time is not None:
            self._step_times.append(float(step_time))
        if tokens:
            self._tokens_total += int(tokens)
        if tm:
            self._last_tracker_metrics = tm
        idx = self._step_idx
        self._step_idx += 1
        if deferred:
            ev = step_event(idx, loss=None, tokens=tokens,
                            step_time_s=step_time,
                            tracker_metrics=tracker_metrics, **extra)
            self._deferred.append((ev, loss))
            return
        if self._deferred:       # keep steps.jsonl in step order
            self.flush()
        if loss is not None:
            self._losses.append(float(loss))
        if self.writer is not None:
            self.writer.append_step(step_event(
                idx, loss=loss, tokens=tokens, step_time_s=step_time,
                tracker_metrics=tracker_metrics, **extra))

    def flush(self, up_to: int | None = None) -> None:
        """Resolve buffered deferred-loss events (oldest first; all of
        them, or the first ``up_to``) and hand them to the writer.  The
        caller — the pump at a sync point, or finalize — is responsible
        for the losses being (near-)ready; resolution of a poisoned
        array degrades to a null loss rather than raising."""
        n = len(self._deferred) if up_to is None \
            else min(up_to, len(self._deferred))
        for _ in range(n):
            ev, arr = self._deferred.pop(0)
            try:
                from ..utils.mesh import local_scalar
                lf = local_scalar(arr)
            except Exception:   # crash path: keep the original exception
                lf = None
            if lf is not None:
                ev["loss"] = lf
                self._losses.append(lf)
            if self.writer is not None:
                self.writer.append_step(ev)
        if self.writer is not None:
            self.writer.flush()

    # ---- end-of-run -----------------------------------------------------
    def _aggregates(self) -> dict:
        out: dict = {
            "steps_recorded": self._step_idx,
            "total_tokens": self._tokens_total,
        }
        if self._losses:
            out["first_loss"] = self._losses[0]
            out["final_loss"] = self._losses[-1]
            out["avg_loss"] = sum(self._losses) / len(self._losses)
        if self._step_times:
            # median over the post-compile tail: step 0 carries the jit
            times = self._step_times[1:] or self._step_times
            out["step_time_ms"] = statistics.median(times) * 1e3
            out["step_time_ms_mean"] = sum(times) / len(times) * 1e3
        tm = self._last_tracker_metrics or {}
        for k in ("tokens_per_second", "steps_per_second",
                  "tflops_per_device", "peak_memory_gb"):
            if tm.get(k) is not None:
                out[k] = tm[k]
        return out

    def finalize(self, status: str = "completed", error: str | None = None,
                 **extra) -> dict | None:
        """Write ``summary.json``.  Idempotent: a crash path overwrites a
        not-yet-written summary only; explicit double calls are no-ops."""
        if self._finalized:
            return None
        self._finalized = True
        try:
            self.flush()     # resolve any still-deferred losses
        except Exception:
            pass
        if not self.enabled or self.writer is None:
            return None
        summary: dict = {
            "run_id": self.run_id,
            "strategy": self.strategy,
            "model": self.model,
            "status": status,
        }
        if error:
            summary["error"] = error
        cfg = self.manifest.config if self.manifest else {}
        for k in ("sequence_length", "batch_size", "num_steps",
                  "precision", "seed"):
            if k in cfg:
                summary[k] = cfg[k]
        summary.update(self._aggregates())
        if self.host_sync_count is not None:
            # the pump's instrumented blocking events (policy barriers +
            # backpressure waits) — the async-dispatch acceptance metric
            summary["host_sync_count"] = self.host_sync_count
            summary["host_sync_breakdown"] = self.host_sync_breakdown
        summary.update(extra)
        # post-run profiling hook: comm/compute split from the trace the
        # owned Profiler just flushed
        prof = self.profiler
        if prof is not None and getattr(prof, "enabled", False):
            summary["trace_dir"] = prof.trace_dir
            try:
                from ..utils.trace_analysis import split_from_trace
                sp = split_from_trace(prof.trace_dir)
            except Exception:   # trace parsing must never fail the run
                sp = None
            if sp is not None:
                summary["comm_split"] = {
                    "comm_us": sp.comm_us,
                    "compute_us": sp.compute_us,
                    "other_us": sp.other_us,
                    "comm_fraction": sp.comm_fraction,
                    "overlap_us": sp.overlap_us,
                    "overlap_fraction": sp.overlap_fraction,
                    "trace_file": sp.trace_file,
                }
        self.writer.write_summary(summary)
        self.writer.close()
        return summary
