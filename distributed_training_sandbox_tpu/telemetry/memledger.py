"""Memory ledger — measured HBM attribution joined to planner waterlines.

The measured twin of ``memory_plan``'s WaterlinePrediction, built the way
``telemetry/ledger.py`` is the measured twin of the collective contract:

* :func:`attribute_categories` parses the compiled step's
  ``memory_analysis()`` breakdown into attributed categories — params,
  opt-state, batch (tree-walked eagerly at attach time, BEFORE donation
  invalidates the buffers), collective scratch (payload bytes of every
  ``ops.hlo.collective_instances`` site in the compiled text),
  remat-policy saved activations (``checkpoint_name`` metadata, where the
  compiled text carries it) and the residual activation workspace —
  keyed by named param paths under the same name normalization the
  collective ledger applies to trace events (leading ``%`` and scope
  prefixes stripped).
* :class:`MemorySampler` is the ONE process-wide poll site over
  ``utils.memory.device_memory_stats`` — ``utils.tracker`` and
  ``utils.memory.all_devices_memory_gb`` both route through
  :func:`get_sampler`, and the span stream feeds it a phase per host
  span so ``memory.json`` records per-phase live-allocator peaks for
  prefetch/dispatch/sync/checkpoint/prefill/decode.
* :func:`join_prediction` produces the MemoryVerdict: measured peak vs
  the compiled ``memory_analysis()`` waterline within a pinned band,
  plus (when the driver recorded one) the analytic/serving prediction
  with per-category residuals — stamped into ``manifest.json`` as the
  third mark beside the static contract and collective-ledger verdicts.

Substrate honesty: CPU-simulated devices expose no allocator stats, so
the measured peak degrades to the compile-side accounting
(args + out + temp − alias) with ``measured_source="accounted"`` —
the attribution and the join still run; real HBM numbers arrive with
``measured_source="allocator"`` on a TPU slice.
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
from dataclasses import dataclass, field
from typing import Any

from ..ops.hlo import _DTYPE_BYTES, _SHAPE_RE, collective_instances
from ..utils.memory import GB, device_memory_stats

MEMORY_FILENAME = "memory.json"
MEMORY_SCHEMA_VERSION = 1

# the phase vocabulary of the live-allocator timeline — every host span
# the SpanStream emits maps into one of these (or none)
PHASES = ("prefetch", "dispatch", "sync", "checkpoint", "prefill", "decode")

# measured/predicted ratio bands by prediction source.  The
# memory_analysis band is tight — on the accounted fallback the ratio is
# exactly 1, and a real allocator peak should sit within fragmentation
# slack of the compiler's plan.  Analytic and serving-accounting bands
# mirror the CPU-mesh calibration pinned by tests/test_memory_plan.py
# (the tight ~10% analytic calibration is against TPU verdicts only).
PREDICTION_BANDS = {
    "memory_analysis": (0.5, 2.0),
    "analytic": (0.2, 5.0),
    "serve_accounting": (0.2, 5.0),
}
DEFAULT_BAND = (0.2, 5.0)


# ------------------------------------------------------------- sampler

class MemorySampler:
    """The single shared device-memory poll site.

    Thread-safe: the span stream samples from whatever thread emits the
    span (prefetcher, checkpoint writer, pump).  Tracks the global and
    per-phase peak of ``max(bytes_in_use, peak_bytes_in_use)`` in GB.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.samples = 0
        self.peak_gb = 0.0
        self.phase_peaks_gb: dict[str, float] = {}
        self.last_stats: dict[str, int] = {}

    def sample(self, phase: str | None = None) -> dict[str, int]:
        """Poll device 0's allocator, fold into the (phase) peaks, and
        return the raw stats dict (zeros on backends without stats)."""
        stats = device_memory_stats()
        hi = max(stats["bytes_in_use"], stats["peak_bytes_in_use"]) / GB
        with self._lock:
            self.samples += 1
            self.last_stats = stats
            if hi > self.peak_gb:
                self.peak_gb = hi
            if phase is not None:
                self.phase_peaks_gb[phase] = max(
                    self.phase_peaks_gb.get(phase, 0.0), hi)
        return stats

    def all_devices_gb(self) -> dict[str, dict[str, float]]:
        """Per-device current/peak GB — the one loop over
        ``jax.local_devices()`` that ``utils.memory.all_devices_memory_gb``
        delegates to."""
        import jax
        out = {}
        for d in jax.local_devices():
            s = device_memory_stats(d)
            out[str(d.id)] = {
                "current_gb": s["bytes_in_use"] / GB,
                "peak_gb": s["peak_bytes_in_use"] / GB,
            }
        return out

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {"samples": self.samples, "peak_gb": self.peak_gb,
                    "phase_peaks_gb": dict(self.phase_peaks_gb)}

    def reset(self) -> None:
        with self._lock:
            self.samples = 0
            self.peak_gb = 0.0
            self.phase_peaks_gb = {}
            self.last_stats = {}


_SAMPLER: MemorySampler | None = None
_SAMPLER_LOCK = threading.Lock()


def get_sampler() -> MemorySampler:
    """The process-wide shared sampler (identity pinned by test)."""
    global _SAMPLER
    with _SAMPLER_LOCK:
        if _SAMPLER is None:
            _SAMPLER = MemorySampler()
        return _SAMPLER


def reset_sampler() -> None:
    """Drop accumulated peaks — test isolation hook."""
    get_sampler().reset()


def phase_for_span(name: str, cat: str | None = None) -> str | None:
    """Map a host span (name, cat) onto the phase vocabulary, or None
    for spans outside the memory timeline (telemetry internals)."""
    name = name or ""
    cat = cat or ""
    if cat == "prefetch" or name.startswith("prefetch"):
        return "prefetch"
    if cat == "checkpoint" or name.startswith("checkpoint"):
        return "checkpoint"
    if "prefill" in name:
        return "prefill"
    if "decode" in name:
        return "decode"
    if cat == "pump" or name.startswith("pump"):
        if any(t in name for t in ("sync", "drain", "throttle")):
            return "sync"
        return "dispatch"
    return None


# --------------------------------------------------------- attribution

def _normalize_name(s: str) -> str:
    """The collective ledger's trace-event name normalization
    (``utils.trace_analysis.normalize_event_name``): leading ``%`` and
    scope prefixes stripped — applied to param paths so the same key
    joins trees, HLO instructions and trace events."""
    return s.lstrip("%").rsplit("/", 1)[-1]


def param_path_bytes(tree: Any, top: int = 32) -> dict[str, int]:
    """Per-named-path byte attribution of a param tree (dot-joined pytree
    path, normalized like HLO instruction names), largest ``top`` paths."""
    import jax
    out: dict[str, int] = {}
    leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in leaves:
        if not hasattr(leaf, "nbytes"):
            continue
        parts = []
        for p in path:
            for attr in ("key", "idx", "name"):
                if hasattr(p, attr):
                    parts.append(str(getattr(p, attr)))
                    break
            else:
                parts.append(str(p))
        name = _normalize_name(".".join(parts))
        out[name] = out.get(name, 0) + int(leaf.nbytes)
    ranked = sorted(out.items(), key=lambda kv: (-kv[1], kv[0]))
    return dict(ranked[:top])


_RESULT_RE = re.compile(r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*"
                        r"(?P<shape>\([^)]*\)|\S+)\s")
_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')
_SAVE_NAME_RE = re.compile(r"checkpoint_name\[\s*name\s*=\s*([\w\-./]+)")


def _shape_bytes(tok: str) -> int:
    total = 0
    for m in _SHAPE_RE.finditer(tok):
        dims = tuple(int(d) for d in m.group(2).split(",")) \
            if m.group(2) else ()
        total += math.prod(dims) * _DTYPE_BYTES.get(m.group(1), 4)
    return total


def saved_activation_bytes(text: str) -> tuple[int, list[str]]:
    """Bytes (and save names) of buffers the remat policy pinned across
    the boundary, where the compiled text carries ``checkpoint_name``
    metadata.  Compilers that drop the metadata yield ``(0, [])`` — the
    'where available' half of the attribution contract."""
    total, names = 0, []
    for raw in text.splitlines():
        op = _OP_NAME_RE.search(raw)
        if not op:
            continue
        save = _SAVE_NAME_RE.search(op.group(1))
        if not save:
            continue
        res = _RESULT_RE.match(raw)
        if not res:
            continue
        total += _shape_bytes(res.group("shape"))
        name = _normalize_name(save.group(1))
        if name not in names:
            names.append(name)
    return total, names


def attribute_categories(mem: dict[str, int],
                         trees_bytes: dict[str, int] | None,
                         hlo_text: str = "") -> tuple[dict[str, int],
                                                      list[str]]:
    """Split the compiled step's ``memory_analysis()`` breakdown into
    attributed byte categories.

    ``mem``: ``{argument_bytes, output_bytes, temp_bytes, alias_bytes}``.
    ``trees_bytes``: eager tree-walk bytes per named argument category
    (params / opt_state / batch / kv_pool ...) — these partition the
    argument buffers; whatever they don't cover lands in
    ``unattributed_args``.  Temps split into collective scratch (summed
    ``collective_instances`` payloads), policy-saved activations and the
    residual ``activations_workspace``.
    """
    args_b = int(mem.get("argument_bytes", 0))
    out_b = int(mem.get("output_bytes", 0))
    temp_b = int(mem.get("temp_bytes", 0))
    scratch = 0
    saved, saved_names = 0, []
    if hlo_text:
        scratch = sum(i.bytes for i in collective_instances(hlo_text))
        saved, saved_names = saved_activation_bytes(hlo_text)
    cats = {k: int(v) for k, v in (trees_bytes or {}).items()}
    cats["unattributed_args"] = max(args_b - sum(cats.values()), 0)
    cats["out"] = out_b
    cats["collective_scratch"] = min(scratch, temp_b)
    # scratch and saved together never exceed temps — the residual
    # workspace stays a true partition remainder, never negative
    cats["saved_activations"] = min(saved,
                                    temp_b - cats["collective_scratch"])
    cats["activations_workspace"] = (
        temp_b - cats["collective_scratch"] - cats["saved_activations"])
    return cats, saved_names


# --------------------------------------------------------------- ledger

@dataclass
class MemoryLedger:
    """Attributed compile-side accounting + the live allocator timeline
    of one run — what ``memory.json`` serializes."""
    categories_gb: dict[str, float]
    param_paths_gb: dict[str, float]
    compiled: dict[str, float]          # argument/output/temp/alias GB
    #                                     + waterline_gb
    phase_peaks_gb: dict[str, float]
    samples: int
    measured_peak_gb: float
    measured_source: str                # "allocator" | "accounted"
    capacity_gb: float | None = None
    saved_names: list[str] = field(default_factory=list)
    prediction_join: dict | None = None

    def to_dict(self) -> dict:
        return {
            "schema": MEMORY_SCHEMA_VERSION,
            "categories_gb": {k: round(v, 9)
                              for k, v in self.categories_gb.items()},
            "param_paths_gb": {k: round(v, 9)
                               for k, v in self.param_paths_gb.items()},
            "compiled": {k: round(v, 9) for k, v in self.compiled.items()},
            "phase_peaks_gb": dict(self.phase_peaks_gb),
            "samples": self.samples,
            "measured_peak_gb": round(self.measured_peak_gb, 9),
            "measured_source": self.measured_source,
            "capacity_gb": self.capacity_gb,
            "saved_names": list(self.saved_names),
            "prediction_join": self.prediction_join,
        }

    def write(self, run_dir: str) -> str:
        path = os.path.join(run_dir, MEMORY_FILENAME)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path


def build_memory_ledger(mem: dict[str, int],
                        trees_bytes: dict[str, int] | None = None,
                        hlo_text: str = "", *,
                        sampler: MemorySampler | None = None,
                        param_paths: dict[str, int] | None = None,
                        capacity_gb: float | None = None) -> MemoryLedger:
    """Join compile-side accounting with the sampler's live timeline.

    The measured peak prefers the allocator (nonzero peak from any
    sample); on stat-less backends it falls back to the accounted
    waterline so the verdict stays meaningful on the CPU tier.
    """
    args_b = int(mem.get("argument_bytes", 0))
    out_b = int(mem.get("output_bytes", 0))
    temp_b = int(mem.get("temp_bytes", 0))
    alias_b = int(mem.get("alias_bytes", 0))
    waterline_gb = (args_b + out_b + temp_b - alias_b) / GB
    cats, saved_names = attribute_categories(mem, trees_bytes, hlo_text)
    snap = sampler.snapshot() if sampler is not None \
        else {"samples": 0, "peak_gb": 0.0, "phase_peaks_gb": {}}
    alloc_peak = float(snap.get("peak_gb", 0.0))
    if alloc_peak > 0.0:
        measured, source = alloc_peak, "allocator"
    else:
        measured, source = waterline_gb, "accounted"
    return MemoryLedger(
        categories_gb={k: v / GB for k, v in cats.items()},
        param_paths_gb={k: v / GB for k, v in (param_paths or {}).items()},
        compiled={"argument_gb": args_b / GB, "output_gb": out_b / GB,
                  "temp_gb": temp_b / GB, "alias_gb": alias_b / GB,
                  "waterline_gb": waterline_gb},
        phase_peaks_gb=dict(snap.get("phase_peaks_gb", {})),
        samples=int(snap.get("samples", 0)),
        measured_peak_gb=measured,
        measured_source=source,
        capacity_gb=capacity_gb,
        saved_names=saved_names,
    )


# ----------------------------------------------------- prediction join

# analytic-component → measured-category aliases (the predictor calls
# the optimizer term "opt"; the attributed tree category is "opt_state")
_COMPONENT_ALIASES = {"opt": "opt_state"}


def join_prediction(ledger: MemoryLedger, prediction: Any = None,
                    strategy: str = "") -> dict:
    """The MemoryVerdict: the measured twin of WaterlinePrediction.judge.

    Always judges the measured peak against the compiled
    ``memory_analysis()`` waterline (the pinned acceptance band); when
    the driver recorded a planner/serving prediction it is judged too,
    within its source's band, with per-category residuals (measured GB −
    predicted component GB over the categories both sides name).  The
    verdict is ``ok`` only when every judged band holds.
    """
    violations: list[str] = []
    measured = ledger.measured_peak_gb
    compiled_gb = ledger.compiled.get("waterline_gb", 0.0)
    lo, hi = PREDICTION_BANDS["memory_analysis"]
    ratio_c = measured / compiled_gb if compiled_gb > 0 else float("inf")
    ok = compiled_gb > 0 and lo < ratio_c < hi
    if not ok:
        violations.append(
            f"measured {measured:.4f} GB vs compiled {compiled_gb:.4f} GB: "
            f"ratio {ratio_c:.3f} outside ({lo}, {hi})")
    verdict: dict[str, Any] = {
        "strategy": strategy,
        "measured_gb": round(measured, 6),
        "measured_source": ledger.measured_source,
        "compiled_gb": round(compiled_gb, 6),
        "compiled_ratio": round(ratio_c, 6) if compiled_gb > 0 else None,
        "compiled_band": [lo, hi],
        "residuals": {},
    }
    if prediction is not None:
        pd = prediction.to_dict() if hasattr(prediction, "to_dict") \
            else dict(prediction)
        pred_gb = pd.get("predicted_gb")
        source = pd.get("source") or "analytic"
        if pred_gb:
            plo, phi = PREDICTION_BANDS.get(source, DEFAULT_BAND)
            ratio_p = measured / float(pred_gb)
            verdict.update(predicted_gb=round(float(pred_gb), 6),
                           predicted_source=source,
                           predicted_ratio=round(ratio_p, 6),
                           predicted_band=[plo, phi])
            if not plo < ratio_p < phi:
                ok = False
                violations.append(
                    f"measured {measured:.4f} GB vs predicted "
                    f"{float(pred_gb):.4f} GB ({source}): ratio "
                    f"{ratio_p:.3f} outside ({plo}, {phi})")
            comps = pd.get("components") or {}
            for k, v in comps.items():
                mk = _COMPONENT_ALIASES.get(k, k)
                if mk in ledger.categories_gb:
                    verdict["residuals"][mk] = round(
                        ledger.categories_gb[mk] - float(v), 6)
    verdict["ok"] = ok
    verdict["violations"] = violations
    ledger.prediction_join = verdict
    return verdict


# ------------------------------------------------ artifacts & the gate

def load_memory_dict(run_dir: str) -> dict | None:
    """``memory.json`` of a run dir as a dict, or None when absent or
    unreadable (mirrors ``ledger.load_ledger_dict``)."""
    path = os.path.join(run_dir, MEMORY_FILENAME)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def memory_aggregates(doc: dict) -> dict[str, float]:
    """Flatten a memory.json dict into the gate's key → GB form: the
    measured peak plus one ``cat/<name>`` key per attributed category."""
    out = {"peak": float(doc.get("measured_peak_gb") or 0.0)}
    for k, v in (doc.get("categories_gb") or {}).items():
        out[f"cat/{k}"] = float(v)
    return out


def check_memory_regressions(cur: dict[str, float],
                             base: dict[str, float],
                             max_growth_pct: float = 20.0,
                             label: str = "",
                             base_label: str = "") -> list[dict]:
    """Direction-aware memory gate: GROWTH is the bad direction (the
    mirror image of the bandwidth gate, where a drop regresses).  Keys
    present on only one side are skipped, not errors."""
    recs = []
    for key in sorted(cur):
        gb, base_gb = cur[key], base.get(key)
        if not base_gb:
            continue
        delta_pct = (gb / base_gb - 1.0) * 100.0
        recs.append({
            "run_id": label, "baseline": base_label, "key": key,
            "gb": gb, "baseline_gb": base_gb,
            "delta_pct": delta_pct, "max_growth_pct": max_growth_pct,
            "regressed": delta_pct > max_growth_pct,
        })
    return recs
