"""Filesystem half of the telemetry layer: one run directory, the
artifacts.

Layout contract (read back by ``telemetry.report`` / ``scripts/report.py``):

    <results_dir>/<run_id>/
        manifest.json     written at startup (RunManifest); rewritten
                          once at finalize when profiling was on, to add
                          the owned profiler sessions + ledger verdict
        steps.jsonl       appended once per optimizer step (schema.step_event)
        spans.jsonl       host-side phase spans (telemetry.spans), when any
        collectives.json  the CollectiveLedger (telemetry.ledger), when
                          profiling captured a trace and the run attached
                          its compiled HLO
        summary.json      written at finalize (and overwritten on crash
                          with status="crashed" so partial runs are visible)

The writer is deliberately dumb — no rank logic, no aggregation; the
rank-0-only policy and the summary contents live in ``TelemetryRun``.

Step appends are buffered and flushed every :data:`FLUSH_EVERY` (= 32)
events, plus explicitly via ``flush()`` (the pump does this at every
sync point) and on ``close()`` — which every crash path reaches through
``TelemetryRun.finalize``.  The durability contract is therefore:
an *exception* loses nothing; a hard kill (SIGKILL/power) loses at most
the ≤ 32 in-flight events since the last flush.  The previous
line-buffered mode paid one write+flush syscall pair per step in the
hot loop for a guarantee only the hard-kill case ever used.
"""

from __future__ import annotations

import json
import os

FLUSH_EVERY = 32


class MetricsWriter:
    MANIFEST = "manifest.json"
    STEPS = "steps.jsonl"
    SUMMARY = "summary.json"

    def __init__(self, run_dir: str, flush_every: int = FLUSH_EVERY):
        self.run_dir = run_dir
        os.makedirs(run_dir, exist_ok=True)
        self._steps_f = None
        self.steps_written = 0
        self.flush_every = max(int(flush_every), 1)
        self._unflushed = 0

    # ---- artifacts ------------------------------------------------------
    def write_manifest(self, manifest) -> str:
        path = os.path.join(self.run_dir, self.MANIFEST)
        d = manifest.to_dict() if hasattr(manifest, "to_dict") else manifest
        with open(path, "w") as f:
            json.dump(d, f, indent=2, default=str)
            f.write("\n")
        return path

    def append_step(self, event: dict) -> None:
        if self._steps_f is None:
            self._steps_f = open(os.path.join(self.run_dir, self.STEPS),
                                 "a")
        self._steps_f.write(json.dumps(event, default=str) + "\n")
        self.steps_written += 1
        self._unflushed += 1
        if self._unflushed >= self.flush_every:
            self.flush()

    def flush(self) -> None:
        if self._steps_f is not None and self._unflushed:
            self._steps_f.flush()
        self._unflushed = 0

    def write_json(self, name: str, obj: dict) -> str:
        """One auxiliary JSON artifact in the run dir (collectives.json
        is the current client)."""
        path = os.path.join(self.run_dir, name)
        with open(path, "w") as f:
            json.dump(obj, f, indent=2, default=str)
            f.write("\n")
        return path

    def write_summary(self, summary: dict) -> str:
        path = os.path.join(self.run_dir, self.SUMMARY)
        with open(path, "w") as f:
            json.dump(summary, f, indent=2, default=str)
            f.write("\n")
        return path

    def close(self) -> None:
        if self._steps_f is not None:
            self.flush()
            self._steps_f.close()
            self._steps_f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
