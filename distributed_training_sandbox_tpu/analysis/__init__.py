"""Static analysis of sharding & collective choreography.

The north star demands each strategy "replays the same collective
choreography" as the reference scripts.  This package makes that a
machine-checked contract instead of a spot-checked print:

  * ``contracts``  — one declarative :class:`CollectiveContract` per
    strategy (expected collective site counts per step, allowed mesh
    axes, approximate payload bytes), checked against
    ``ops.hlo.count_collectives`` of the lowered step;
  * ``hlo_lint``   — lint passes over *compiled* HLO text: accidental
    full-param replication (unexpected all-gather of a full param
    shape), missing input/output buffer aliasing where donation was
    requested, host transfers, and collectives whose replica groups
    don't correspond to any declared mesh axis;
  * ``recompile``  — retrace counter over a step-function window;
    recompiles after the first executed step fail;
  * ``pitfalls``   — AST-level lint of ``scripts/`` for classic JAX
    performance traps (hot jnp ops in Python loops outside jit,
    collectives outside shard_map, train-step jits without donation);
  * ``fixtures``   — tiny CPU-mesh builds of every strategy's train
    step, shared by the contract pytest suite and the lint CLI.

Entry point: ``scripts/lint_sharding.py`` (exit nonzero on violation,
``--json`` report); per-run verdicts land in telemetry ``manifest.json``.
"""

from .contracts import (  # noqa: F401
    CONTRACTS,
    CollectiveContract,
    ContractContext,
    ContractVerdict,
    check_counts,
    evaluate_contract,
)
from .hlo_lint import LintFinding, lint_compiled_hlo  # noqa: F401
from .recompile import RecompileReport, watch_recompiles  # noqa: F401
from .pitfalls import PitfallFinding, lint_file, lint_tree  # noqa: F401
