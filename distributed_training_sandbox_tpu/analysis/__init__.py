"""Static analysis of sharding & collective choreography.

The north star demands each strategy "replays the same collective
choreography" as the reference scripts.  This package makes that a
machine-checked contract instead of a spot-checked print:

  * ``contracts``  — one declarative :class:`CollectiveContract` per
    strategy (expected collective site counts per step, allowed mesh
    axes, approximate payload bytes), checked against
    ``ops.hlo.count_collectives`` of the lowered step;
  * ``rules``      — ordered ``(regex, PartitionSpec)`` partition rules
    per strategy family (:class:`RuleSet`, the zero1/2/3 family folded
    into a ``weight_update_sharding`` axis): the declarative source of
    truth PartitionSpecs, contracts, and drift checks derive from, with
    static rule hygiene (unmatched leaf / dead rule / shadowed rule);
  * ``contract_gen`` — generate each strategy's CollectiveContract from
    its RuleSet; :func:`diff_all_contracts` proves the generator against
    the hand registry field-by-field;
  * ``hlo_lint``   — lint passes over *compiled* HLO text: accidental
    full-param replication (unexpected all-gather of a full param
    shape), missing input/output buffer aliasing where donation was
    requested, host transfers, and collectives whose replica groups
    don't correspond to any declared mesh axis;
  * ``recompile``  — retrace counter over a step-function window;
    recompiles after the first executed step fail;
  * ``pitfalls``   — AST-level lint of ``scripts/`` for classic JAX
    performance traps (hot jnp ops in Python loops outside jit,
    collectives outside shard_map, train-step jits without donation);
  * ``fixtures``   — tiny CPU-mesh builds of every strategy's train
    step, shared by the contract pytest suite and the lint CLI.

Entry point: ``scripts/lint_sharding.py`` (exit nonzero on violation,
``--json`` report); per-run verdicts land in telemetry ``manifest.json``.
"""

from .contracts import (  # noqa: F401
    CONTRACTS,
    CollectiveContract,
    ContractContext,
    ContractVerdict,
    check_counts,
    evaluate_contract,
)
from .contract_gen import (  # noqa: F401
    ContractDiff,
    diff_all_contracts,
    diff_contract,
    generate_all_contracts,
    generate_contract,
)
from .hlo_lint import (  # noqa: F401
    LintFinding,
    check_sharding_drift,
    lint_compiled_hlo,
)
from .rules import (  # noqa: F401
    MatchReport,
    Rule,
    RULESETS,
    RuleSet,
    expected_arg_specs,
    match_partition_rules,
    rules_manifest_verdict,
)
from .recompile import RecompileReport, watch_recompiles  # noqa: F401
from .pitfalls import PitfallFinding, lint_file, lint_tree  # noqa: F401
