"""Generate CollectiveContracts from partition RuleSets.

The hand-registered :data:`~.contracts.CONTRACTS` formulas were each
calibrated against one lowered step.  This module derives the same
contracts *structurally* from :data:`~.rules.RULESETS`: which leaves a
strategy shards at rest (gather sites), how its ``weight_update_sharding``
level moves the gradient reduction (all_reduce vs reduce_scatter vs
nothing-at-rank), and which wire format / overlap decomposition its
config picks — so a new axis combination costs a RuleSet entry, not a
hand-calibrated formula.

:func:`diff_all_contracts` is the proof the generator is trustworthy: it
evaluates generated vs hand contracts field-by-field over a synthetic
:class:`~.contracts.ContractContext` grid covering every registered
strategy and reports any divergence.  Each divergence is either a
generator bug or a latent calibration bug in the hand contract — the
tier-1 ``rules`` tests pin the diff to empty.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

from .contracts import (
    CONTRACTS,
    CollectiveContract,
    ContractContext,
    KINDS,
    N_PROJ_LEAVES,
    _offload_host_transfers,
    ddp_bucket_count,
)
from .rules import RULESETS, RuleSet

# ---------------------------------------------------------------- counts
#
# Shared structural facts the derivations lean on, with the calibrated
# constants they produce:
#   * scanned train steps collapse depth: one site per stacked leaf;
#   * remat re-runs forward gathers inside the backward scan (2x hop
#     upper bounds for ring decompositions, n-1 backward re-gathers for
#     the per-layer W3 MLP whose last bias needs no recompute);
#   * the toy-MLP data-parallel steps carry a loss-mean all_reduce and a
#     step barrier (+2); the scanned transformer steps carry only the
#     loss pmean (+1); serving carries neither.


def _grad_buckets(c: ContractContext) -> int:
    """Flat ~MB gradient bucket count for the bucketed/q8 wire formats
    (per dtype group when the run recorded a dtype split)."""
    import numpy as np
    bucket_mb = float(c.extra.get("bucket_mb") or 25.0)
    dtype_bytes = c.extra.get("dtype_bytes")
    if dtype_bytes:
        return sum(ddp_bucket_count(b, bucket_mb, np.dtype(dt).itemsize)
                   for dt, b in dtype_bytes.items())
    return ddp_bucket_count(c.param_bytes, bucket_mb)


def _data_counts(rs: RuleSet) -> Callable[[ContractContext], dict]:
    """Data-parallel family: the ``weight_update_sharding`` axis of
    arXiv:2004.13336 decides where the gradient lands and what must be
    rebuilt, the ``grad_comm`` knob decides the W0 wire format."""
    w = rs.weight_update_sharding
    comm = rs.config.get("grad_comm", "allreduce")

    def counts(c: ContractContext) -> dict:
        n = c.n_leaves
        if w == 0:
            # replicated update: grads cross the wire, params never do
            if comm == "bucketed":
                return {"all_reduce": _grad_buckets(c) + 2}
            if comm == "q8":
                # int8 codes + f32 scale ride gathers per flat bucket;
                # only loss mean + barrier stay all_reduces
                return {"all_reduce": 2, "all_gather": 2 * _grad_buckets(c)}
            return {"all_reduce": n + 2}
        if w == 1:
            # sharded opt state: n grad all_reduces + n param rebuilds
            if c.extra.get("rebuild", "broadcast") == "all_gather":
                return {"all_reduce": n + 2, "all_gather": n}
            return {"all_reduce": 2 * n + 2}  # masked-psum broadcast twin
        if w == 2:
            # + sharded reduction: grads reduce_scatter straight to chunk
            if c.extra.get("rebuild", "broadcast") == "all_gather":
                return {"all_reduce": 2, "all_gather": n,
                        "reduce_scatter": n}
            return {"all_reduce": n + 2, "reduce_scatter": n}
        # W3, per-layer materialize: n fwd gathers + (n-1) remat'd bwd
        # re-gathers (the last bias has no recompute consumer), grads
        # arrive through the gather transpose (one psum_scatter each)
        return {"all_reduce": 2, "all_gather": 2 * n - 1,
                "reduce_scatter": n}

    return counts


def _fsdp_counts(rs: RuleSet) -> Callable[[ContractContext], dict]:
    """FSDP family: one gather + one reduce-scatter site per stacked
    leaf (scan collapses depth), one loss pmean; the overlap knob
    rewrites gather sites into ring ppermute hops, optionally fusing the
    projection matmuls into the ring."""
    overlap = rs.config.get("overlap", "none")
    axis = rs.axes[0]

    def counts(c: ContractContext) -> dict:
        n = c.n_leaves
        ws = c.axis_sizes.get(axis, c.ws)
        if overlap == "ring":
            hops = n * (ws - 1)
            return {"all_reduce": 1, "reduce_scatter": n,
                    "collective_permute": (hops, 2 * hops)}
        if overlap == "ring_fused_pallas":
            # the 7 dense projection leaves never materialize: fwd hop
            # ring (all_gather_matmul) + bwd dW ring each, no
            # gather/scatter sites; the rest keep the plain ring
            unfused = n - N_PROJ_LEAVES
            hops = (unfused + 2 * N_PROJ_LEAVES) * (ws - 1)
            return {"all_reduce": 1, "reduce_scatter": unfused,
                    "collective_permute": (hops, 2 * hops)}
        return {"all_reduce": 1, "all_gather": n, "reduce_scatter": n}

    return counts


def _tp_counts(rs: RuleSet) -> Callable[[ContractContext], dict]:
    """Megatron TP: 2 activation rejoin sites per (scanned) layer body +
    per-leaf grad psums; never a param gather.  The overlap knob rewrites
    the 2 rejoin sites (ring: psum_scatter + ppermute hops; q8: two-shot
    quantized gathers of codes + scales)."""
    overlap = rs.config.get("overlap", "none")

    def counts(c: ContractContext) -> dict:
        n = c.n_leaves
        if overlap == "ring":
            tp = c.axis_sizes.get("tp", 2)
            return {"all_reduce": (n, n + 6), "reduce_scatter": 2,
                    "collective_permute": 2 * (tp - 1)}
        if overlap == "q8":
            return {"all_reduce": (n, n + 6), "all_gather": 4}
        return {"all_reduce": (n + 2, n + 8)}

    return counts


def _sp_counts(rs: RuleSet) -> Callable[[ContractContext], dict]:
    """fsdp placement over dp + the KV ring over sp: fsdp's sites, the
    loss pmean joined by per-leaf sp grad psums (+2 -> n+2), and the
    ring's 4 ppermute sites (k and v, forward + backward)."""
    def counts(c: ContractContext) -> dict:
        n = c.n_leaves
        return {"all_reduce": n + 2, "all_gather": n,
                "reduce_scatter": n, "collective_permute": 4}
    return counts


def _composable_counts(rs: RuleSet) -> Callable[[ContractContext], dict]:
    """dp×fsdp×tp (``parallel.composable._make_dp_fsdp_tp_step``): the
    fsdp mechanism contributes one gather + one reduce-scatter site per
    stacked leaf (scan collapses depth, backward re-gathers share the
    forward sites); the tp layer body its 2 rejoin psums; the grad sync
    one fused psum per leaf over the axes it is replicated on; plus the
    loss pmean.  The rejoin/pmean psums fuse unpredictably across
    remat boundaries, hence the range on all_reduce (mirroring the hand
    tp family's calibration)."""
    def counts(c: ContractContext) -> dict:
        n = c.n_leaves
        return {"all_reduce": (n + 1, n + 8), "all_gather": n,
                "reduce_scatter": n}
    return counts


def _moe_counts(rs: RuleSet) -> Callable[[ContractContext], dict]:
    """Switch-MoE: a2a dispatch + return in the scanned body, each with
    its backward transpose (4 sites); dense/router grads psum'd."""
    def counts(c: ContractContext) -> dict:
        n = c.n_leaves
        return {"all_reduce": (n + 2, n + 8), "all_to_all": 4}
    return counts


def _serve_counts(rs: RuleSet) -> Callable[[ContractContext], dict]:
    """Serving decode: inference-only and UNROLLED over layers (static
    layer index into the KV pools), so the 2 rejoin psums scale with
    depth instead of collapsing like the scanned train steps."""
    def counts(c: ContractContext) -> dict:
        return {"all_reduce": 2 * c.n_layers}
    return counts


def _pipeline_counts(rs: RuleSet) -> Callable[[ContractContext], dict]:
    return lambda c: {}


_FAMILY_COUNTS = {
    "data": _data_counts,
    "fsdp": _fsdp_counts,
    "tp": _tp_counts,
    "sp": _sp_counts,
    "composable": _composable_counts,
    "moe": _moe_counts,
    "serve": _serve_counts,
    "pipeline": _pipeline_counts,
}


# ------------------------------------------------------------- generation

def generate_contract(strategy: str) -> CollectiveContract:
    """Derive the CollectiveContract for ``strategy`` from its RuleSet —
    same dataclass, same evaluate/check machinery as the hand registry."""
    rs = RULESETS.get(strategy)
    if rs is None:
        raise KeyError(f"no RuleSet registered for {strategy!r}; "
                       f"have {sorted(RULESETS)}")
    counts = _FAMILY_COUNTS[rs.family](rs)

    # Full-param gathers are by-design exactly when weights are sharded
    # at rest and the step materializes them per layer: W3 (flat chunks
    # or named dims) and the sp composite that embeds fsdp.
    gathers_params = (rs.weight_update_sharding >= 3
                      or rs.family in ("fsdp", "sp"))

    # Payload estimate is param-tree-derivable only when the wire
    # traffic is the grad/param stream itself (data + fsdp families);
    # activation payloads (tp/sp/moe/serve) aren't.
    payload = None
    if rs.family == "data":
        w = rs.weight_update_sharding
        if w == 0 and rs.config.get("grad_comm") == "q8":
            payload = lambda c: c.param_bytes // 4  # int8 codes ride 1x
        elif w == 0:
            payload = lambda c: 2 * c.param_bytes   # all_reduce = 2x
        else:
            payload = lambda c: 3 * c.param_bytes   # reduce + rebuild
    elif rs.family == "fsdp":
        payload = lambda c: 3 * c.param_bytes

    host_transfers = (_offload_host_transfers
                      if rs.config.get("offload") else None)

    return CollectiveContract(
        strategy=strategy,
        axes=rs.axes,
        counts=counts,
        allows_full_param_gather=gathers_params,
        payload_bytes=payload,
        host_transfers=host_transfers,
        description=f"generated from RuleSet[{strategy}]: "
                    f"{rs.description}")


def generate_all_contracts() -> dict[str, CollectiveContract]:
    return {s: generate_contract(s) for s in RULESETS}


# ------------------------------------------------------------------ differ

def _context_grid(strategy: str) -> list[ContractContext]:
    """Synthetic contexts exercising every formula branch a strategy's
    contract can take: world sizes, leaf counts, param sizes, rebuild
    modes, bucket sizes, offload plans, layer depths."""
    rs = RULESETS[strategy]
    grids: list[ContractContext] = []

    def ctx(axis_sizes, n_leaves=12, param_bytes=4 * 2 ** 20,
            n_layers=4, **extra):
        import math
        ws = int(math.prod(axis_sizes.values())) if axis_sizes else 1
        grids.append(ContractContext(
            ws=ws, axis_sizes=dict(axis_sizes), n_leaves=n_leaves,
            n_layers=n_layers, param_bytes=param_bytes, extra=extra))

    if rs.family == "data":
        for dp in (2, 8):
            for n, pb in ((12, 123_456), (6, 4 * 2 ** 20)):
                ctx({"dp": dp}, n_leaves=n, param_bytes=pb)
                ctx({"dp": dp}, n_leaves=n, param_bytes=pb,
                    rebuild="all_gather")
                ctx({"dp": dp}, n_leaves=n, param_bytes=pb,
                    rebuild="broadcast", bucket_mb=0.05)
                ctx({"dp": dp}, n_leaves=n, param_bytes=pb,
                    bucket_mb=25.0,
                    dtype_bytes={"float32": pb // 2, "bfloat16": pb // 2})
    elif rs.family == "fsdp":
        for dp in (2, 8):
            for n in (13, 36):
                ctx({"dp": dp}, n_leaves=n)
                ctx({"dp": dp}, n_leaves=n,
                    offload={"mode": "opt", "supported": True,
                             "n_state_leaves": n, "state_bytes": 2 ** 20})
                ctx({"dp": dp}, n_leaves=n,
                    offload={"mode": "opt", "supported": False})
    elif rs.family in ("tp", "serve"):
        for tp in (2, 4, 8):
            axes = ({"tp": tp} if rs.family == "serve"
                    else {"dp": 8 // tp if tp < 8 else 1, "tp": tp})
            for n, L in ((13, 2), (13, 4)):
                ctx(axes, n_leaves=n, n_layers=L)
    elif rs.family == "sp":
        for dp, sp in ((2, 4), (4, 2)):
            ctx({"dp": dp, "sp": sp}, n_leaves=13)
    elif rs.family == "composable":
        for dp, f, tp in ((2, 2, 2), (1, 2, 2), (2, 4, 2), (2, 2, 4)):
            for n, L in ((11, 2), (11, 4)):
                ctx({"dp": dp, "fsdp": f, "tp": tp}, n_leaves=n,
                    n_layers=L)
    elif rs.family == "moe":
        for dp, ep in ((2, 4), (4, 2)):
            ctx({"dp": dp, "ep": ep}, n_leaves=16)
    else:  # pipeline
        ctx({}, n_leaves=6)
        ctx({}, n_leaves=8, n_layers=8)
    return grids


def _norm_counts(d: dict) -> dict:
    """Counts dict -> comparable form over all KINDS (missing = 0)."""
    out = {}
    for kind in KINDS:
        v = d.get(kind, 0)
        if isinstance(v, tuple):
            v = (int(v[0]), int(v[1]))
        elif v is not None:
            v = int(v)
        out[kind] = v
    return out


@dataclass
class ContractDiff:
    """Field-level divergences between the generated contract and its
    hand-registered twin for one strategy (empty = they agree)."""
    strategy: str
    divergences: list = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.divergences

    def describe(self) -> str:
        head = f"[{self.strategy}] " + ("agree" if self.ok
                                        else "DIVERGE")
        return "\n".join([head] + [f"  {d}" for d in self.divergences])


def diff_contract(strategy: str,
                  ctxs: list[ContractContext] | None = None
                  ) -> ContractDiff:
    """Cross-check generated vs hand contract for one strategy: static
    fields plus counts / payload / host-transfer evaluations over the
    context grid."""
    diff = ContractDiff(strategy)
    hand = CONTRACTS.get(strategy)
    if hand is None:
        diff.divergences.append("no hand-registered contract")
        return diff
    gen = generate_contract(strategy)
    if tuple(gen.axes) != tuple(hand.axes):
        diff.divergences.append(
            f"axes: generated {gen.axes} vs hand {hand.axes}")
    if gen.allows_full_param_gather != hand.allows_full_param_gather:
        diff.divergences.append(
            f"allows_full_param_gather: generated "
            f"{gen.allows_full_param_gather} vs hand "
            f"{hand.allows_full_param_gather}")
    if (gen.host_transfers is None) != (hand.host_transfers is None):
        diff.divergences.append(
            f"host_transfers: generated "
            f"{'declared' if gen.host_transfers else 'absent'} vs hand "
            f"{'declared' if hand.host_transfers else 'absent'}")
    if (gen.payload_bytes is None) != (hand.payload_bytes is None):
        diff.divergences.append(
            f"payload_bytes: generated "
            f"{'estimated' if gen.payload_bytes else 'None'} vs hand "
            f"{'estimated' if hand.payload_bytes else 'None'}")
    for c in (ctxs if ctxs is not None else _context_grid(strategy)):
        tag = (f"ws={c.ws} axes={dict(c.axis_sizes)} n={c.n_leaves} "
               f"L={c.n_layers} extra={dict(c.extra)}")
        g, h = _norm_counts(gen.counts(c)), _norm_counts(hand.counts(c))
        for kind in KINDS:
            if g[kind] != h[kind]:
                diff.divergences.append(
                    f"counts[{kind}] @ {tag}: generated {g[kind]} vs "
                    f"hand {h[kind]}")
        if gen.payload_bytes and hand.payload_bytes:
            gp, hp = int(gen.payload_bytes(c)), int(hand.payload_bytes(c))
            if gp != hp:
                diff.divergences.append(
                    f"payload_bytes @ {tag}: generated {gp} vs hand {hp}")
        if gen.host_transfers and hand.host_transfers:
            gt, ht = gen.host_transfers(c), hand.host_transfers(c)
            if dict(gt) != dict(ht):
                diff.divergences.append(
                    f"host_transfers @ {tag}: generated {gt} vs "
                    f"hand {ht}")
    return diff


def diff_all_contracts() -> dict[str, ContractDiff]:
    """The full cross-check: every strategy known to either registry
    (one-sided registrations count as divergences)."""
    out = {}
    for strategy in sorted(set(CONTRACTS) | set(RULESETS)):
        if strategy not in RULESETS:
            d = ContractDiff(strategy)
            d.divergences.append("hand contract has no RuleSet twin")
            out[strategy] = d
        else:
            out[strategy] = diff_contract(strategy)
    return out


# --------------------------------------------------- generated registry
#
# The composable mesh driver's strategies have NO hand-written contract
# by design (the tentpole of ROADMAP item 1): their registry entry IS
# the generated one, installed at import time so evaluate_contract /
# hlo_lint / the drift differ see them exactly like any calibrated
# strategy.  diff_contract for these trivially agrees — the point is
# that the formula's provenance is the RuleSet, not a calibration pass.
GENERATED_STRATEGIES = ("composable_zero1", "composable_dp_fsdp_tp")

for _name in GENERATED_STRATEGIES:
    CONTRACTS[_name] = generate_contract(_name)
del _name
