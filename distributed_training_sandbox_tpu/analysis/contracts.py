"""Per-strategy collective choreography contracts.

A :class:`CollectiveContract` states, declaratively, what one optimizer
step of a strategy is allowed to put on the wire: which collective kinds
appear at how many *sites* in the lowered StableHLO, over which mesh
axes, and roughly how many bytes.  The counts are **site counts** — the
number the tests and every script's startup print already compute via
``ops.hlo.count_collectives`` — so a ``lax.scan`` over layers contributes
its body's collectives once regardless of depth (that is also why the
counts are stable across model sizes of the same family).

The formulas mirror the reference's prose collective accounting
(reference ``README.md:16-20``: "+60 all_reduce +60 broadcast" for 12
params × 5 steps of ZeRO-1) but are evaluated mechanically: a refactor
that silently replicates a sharded param (an extra all-gather) or drops
a reduce-scatter fails the contract instead of drifting by eye.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Callable, Mapping

KINDS = ("all_reduce", "all_gather", "reduce_scatter",
         "collective_permute", "all_to_all")


def _tree_stats(params) -> tuple[int, int]:
    """(leaf count, total param bytes) of a pytree of arrays."""
    import jax
    leaves = [l for l in jax.tree.leaves(params) if hasattr(l, "shape")]
    nbytes = sum(math.prod(l.shape) * getattr(l.dtype, "itemsize", 4)
                 for l in leaves)
    return len(leaves), int(nbytes)


@dataclass(frozen=True)
class ContractContext:
    """Everything a contract formula may depend on, captured from the run
    being checked: world size, mesh axis sizes, parameter tree stats and
    strategy knobs (``extra`` — e.g. the ZeRO rebuild mode)."""
    ws: int = 1
    axis_sizes: Mapping[str, int] = field(default_factory=dict)
    n_leaves: int = 0
    n_layers: int = 0
    param_bytes: int = 0
    extra: Mapping[str, Any] = field(default_factory=dict)

    @classmethod
    def capture(cls, *, params=None, mesh=None, n_layers: int = 0,
                **extra) -> "ContractContext":
        n_leaves = param_bytes = 0
        if params is not None:
            n_leaves, param_bytes = _tree_stats(params)
        axis_sizes = dict(mesh.shape) if mesh is not None else {}
        ws = int(math.prod(axis_sizes.values())) if axis_sizes else 1
        return cls(ws=ws, axis_sizes=axis_sizes, n_leaves=n_leaves,
                   n_layers=n_layers, param_bytes=param_bytes, extra=extra)


@dataclass(frozen=True)
class CollectiveContract:
    """Declarative choreography of one strategy's train step.

    ``counts(ctx)`` maps collective kind -> expected StableHLO site
    count: an int (exact), a ``(lo, hi)`` range (inclusive), or None
    (unchecked).  Kinds missing from the dict are expected to be 0.
    ``axes``: the mesh axes this strategy's collectives may span —
    the replica-group check in ``hlo_lint`` enforces it on compiled HLO.
    ``allows_full_param_gather``: strategies that materialize full params
    by design (ZeRO-3 / FSDP / SP) — exempt from the replication lint.
    ``payload_bytes(ctx)``: approximate per-step bytes on the wire, for
    the manifest / report (informational, never asserted).
    ``host_transfers(ctx)``: declared MoveToHost/MoveToDevice custom-call
    count ranges for strategies whose choreography *includes* host
    offload (``memory_plan.OffloadPlan.host_transfer_counts``, read off
    ``ctx.extra["offload"]``) — turns ``hlo_lint``'s host-transfer check
    from forbid into count-check; None keeps the strict forbid."""
    strategy: str
    axes: tuple[str, ...]
    counts: Callable[[ContractContext], dict]
    allows_full_param_gather: bool = False
    payload_bytes: Callable[[ContractContext], int] | None = None
    host_transfers: Callable[[ContractContext], dict] | None = None
    description: str = ""


# ---------------------------------------------------------------- registry
#
# Calibrated against the lowered steps of the in-repo factories (see
# tests/test_contracts.py, which re-derives several of these by lowering
# on the CPU mesh).  n = param leaf count throughout.

def ddp_bucket_count(param_bytes: int, bucket_mb: float,
                     itemsize: int = 4) -> int:
    """Expected all-reduce *site* count of ``parallel.ddp.bucket_gradients``
    for one dtype group: the concatenated flat gradient vector is split
    into exact-capacity chunks of ``bucket_mb`` MB, so the count is just
    ``ceil(elements / chunk_elements)``.  Mirrors the implementation's
    integer arithmetic (capacity floors to whole elements)."""
    cap_elems = max(int(bucket_mb * 2 ** 20) // itemsize, 1)
    n_elems = -(-int(param_bytes) // itemsize)
    return max(-(-n_elems // cap_elems), 1)


def _ddp_bucketed_counts(c: ContractContext) -> dict:
    """Bucketed grad sync + loss mean + barrier.  ``bucket_mb`` comes from
    the run's knobs (ctx.extra); ``dtype_bytes`` (dtype name -> bytes) may
    refine the formula for mixed-precision trees, else all param bytes are
    assumed one 4-byte dtype — exact for the fp32 toy models."""
    import numpy as np
    bucket_mb = float(c.extra.get("bucket_mb") or 25.0)
    dtype_bytes = c.extra.get("dtype_bytes")
    if dtype_bytes:
        n = sum(ddp_bucket_count(b, bucket_mb, np.dtype(dt).itemsize)
                for dt, b in dtype_bytes.items())
    else:
        n = ddp_bucket_count(c.param_bytes, bucket_mb)
    return {"all_reduce": n + 2}


def _ddp_q8_counts(c: ContractContext) -> dict:
    """int8 quantized grad sync: per flat bucket one all_gather of the
    int8 codes + one of the f32 scale (the loss mean + barrier stay
    all_reduces).  Bucket count = the same closed formula as
    ddp_bucketed (capacity floors to whole elements of the ORIGINAL
    grad dtype — quantization happens after bucketing)."""
    import numpy as np
    bucket_mb = float(c.extra.get("bucket_mb") or 25.0)
    dtype_bytes = c.extra.get("dtype_bytes")
    if dtype_bytes:
        n = sum(ddp_bucket_count(b, bucket_mb, np.dtype(dt).itemsize)
                for dt, b in dtype_bytes.items())
    else:
        n = ddp_bucket_count(c.param_bytes, bucket_mb)
    return {"all_reduce": 2, "all_gather": 2 * n}


def _fsdp_ring_counts(c: ContractContext) -> dict:
    """fsdp with the gathers ring-decomposed: every all_gather site
    becomes ws-1 collective_permute hops (rank-order chunk placement);
    the backward stays the monolithic psum_scatter per leaf (pinned by
    the ring op's custom_vjp, which is also what makes the variant
    bitwise-identical).  Remat re-runs the forward ring in the backward
    scan, hence the 2x upper bound."""
    ws = c.axis_sizes.get("dp", c.ws)
    hops = c.n_leaves * (ws - 1)
    return {"all_reduce": 1, "reduce_scatter": c.n_leaves,
            "collective_permute": (hops, 2 * hops)}


# dense transformer projection leaves per layer (wq wk wv wo w_gate
# w_up w_down) — the leaves the ring_fused modes keep sharded and run
# as collective matmuls.  Constant for the dense family; MoE is
# rejected by the fused modes' validation.
N_PROJ_LEAVES = 7


def _fsdp_ring_fused_pallas_counts(c: ContractContext) -> dict:
    """fsdp with the projection matmuls fused into the gather ring
    (Pallas chunk-matmul engine): the 7 projection leaves never
    materialize — each runs ws-1 ppermute hops forward (all_gather_matmul)
    and ws-1 backward (the dW ring of matmul_reduce_scatter's transpose);
    the remaining leaves (norms, embed, final_norm) keep the plain ring
    gather with its monolithic psum_scatter backward.  Remat re-runs
    forward rings in the backward scan, hence the 2x upper bound."""
    ws = c.axis_sizes.get("dp", c.ws)
    unfused = c.n_leaves - N_PROJ_LEAVES
    hops = (unfused + 2 * N_PROJ_LEAVES) * (ws - 1)
    return {"all_reduce": 1, "reduce_scatter": unfused,
            "collective_permute": (hops, 2 * hops)}


def _tp_q8_counts(c: ContractContext) -> dict:
    """tp with the two per-layer rejoin psums running as EQuARX two-shot
    quantized all-reduces: each rejoin site becomes 2 all_gather sites
    (int8 codes + f32 scales over the same tp group) and leaves the
    all_reduce budget to the rejoins' full-precision backward psums,
    per-leaf grad psums and the loss mean."""
    return {"all_reduce": (c.n_leaves, c.n_leaves + 6), "all_gather": 4}


def _tp_ring_counts(c: ContractContext) -> dict:
    """tp with the two per-layer rejoin psums decomposed into
    psum_scatter + ring all-gather: 2 reduce_scatter sites, tp-1 hops
    each, and the rejoins' backward psums (custom_vjp) fold into the
    same all_reduce budget the baseline's transposes used."""
    tp = c.axis_sizes.get("tp", 2)
    return {"all_reduce": (c.n_leaves, c.n_leaves + 6),
            "reduce_scatter": 2,
            "collective_permute": 2 * (tp - 1)}


def _zero1_counts(c: ContractContext) -> dict:
    if c.extra.get("rebuild", "broadcast") == "all_gather":
        return {"all_reduce": c.n_leaves + 2, "all_gather": c.n_leaves}
    # masked-psum rebuild: the wire twin of per-param dist.broadcast
    return {"all_reduce": 2 * c.n_leaves + 2}


def _zero2_counts(c: ContractContext) -> dict:
    if c.extra.get("rebuild", "broadcast") == "all_gather":
        return {"all_reduce": 2, "all_gather": c.n_leaves,
                "reduce_scatter": c.n_leaves}
    return {"all_reduce": c.n_leaves + 2, "reduce_scatter": c.n_leaves}


def _offload_host_transfers(c: ContractContext) -> dict:
    """The declared per-step MoveToHost/MoveToDevice count ranges, read
    off the :class:`memory_plan.OffloadPlan` dict the step build put in
    ``ctx.extra["offload"]``.  An unsupported-backend fallback build
    declares zero — the lint then *forbids* transfers, so the fallback
    is checked, not waved through."""
    from ..memory_plan.offload import OffloadPlan
    plan = c.extra.get("offload") or {}
    if isinstance(plan, OffloadPlan):
        return plan.host_transfer_counts()
    return OffloadPlan(
        mode=plan.get("mode", "none"),
        supported=bool(plan.get("supported")),
        n_state_leaves=int(plan.get("n_state_leaves", 0)),
        state_bytes=int(plan.get("state_bytes", 0)),
        act_names=tuple(plan.get("act_names") or ()),
    ).host_transfer_counts()


CONTRACTS: dict[str, CollectiveContract] = {
    # per-param grad all_reduce + loss mean + step barrier (DDP/ddp.py:43-47)
    "ddp": CollectiveContract(
        "ddp", ("dp",),
        lambda c: {"all_reduce": c.n_leaves + 2},
        payload_bytes=lambda c: 2 * c.param_bytes,
        description="per-param grad all_reduce; no gathers (params "
                    "replicated at rest)"),
    # grads flattened per dtype into ~bucket_mb flat buckets, one
    # all_reduce per bucket (+ loss mean + barrier) — torch DDP's bucketed
    # sync; count is a closed formula over param bytes and bucket size
    "ddp_bucketed": CollectiveContract(
        "ddp_bucketed", ("dp",), _ddp_bucketed_counts,
        payload_bytes=lambda c: 2 * c.param_bytes,
        description="ceil(param_bytes/bucket) grad all_reduces over flat "
                    "buckets + loss mean + barrier; no gathers"),
    # grads quantized to int8 in flat buckets, shipped as all_gathers of
    # (codes, per-bucket scale) and summed after the wire — ~8x less bus
    # traffic than the f32 all_reduce (EQuARX, arXiv:2506.17615)
    "ddp_q8": CollectiveContract(
        "ddp_q8", ("dp",), _ddp_q8_counts,
        # int8 codes ride a gather (1x the quantized payload on the wire)
        # vs the f32 all_reduce's 2x full payload
        payload_bytes=lambda c: c.param_bytes // 4,
        description="2 all_gathers (int8 codes + scale) per flat grad "
                    "bucket + loss mean + barrier; no f32 all_reduces "
                    "on the grad path"),
    # grads all_reduced per param, owner-chunk Adam, per-param rebuild
    "zero1": CollectiveContract(
        "zero1", ("dp",), _zero1_counts,
        payload_bytes=lambda c: 3 * c.param_bytes,
        description="n grad all_reduces + n param rebuilds "
                    "(the reference's 60+60 per 5 steps) + loss + barrier"),
    # grads reduce_scattered straight to the chunk (zero2.py:94-115)
    "zero2": CollectiveContract(
        "zero2", ("dp",), _zero2_counts,
        payload_bytes=lambda c: 3 * c.param_bytes,
        description="n grad reduce_scatters + n param rebuilds + loss + "
                    "barrier"),
    # params sharded at rest; per-layer materialize in fwd AND remat'd bwd
    # (zero3.py:56-77).  Sites: n fwd gathers + (n-1) bwd re-gathers — the
    # last layer's bias needs no recompute (no ReLU mask after it), so its
    # backward gather is dead-code-eliminated.  Grads arrive through the
    # all_gather transpose: one psum_scatter per param.
    "zero3": CollectiveContract(
        "zero3", ("dp",),
        lambda c: {"all_reduce": 2,
                   "all_gather": 2 * c.n_leaves - 1,
                   "reduce_scatter": c.n_leaves},
        allows_full_param_gather=True,
        payload_bytes=lambda c: 3 * c.param_bytes,
        description="per-layer fwd+bwd all_gathers, psum_scatter grads, "
                    "loss + barrier"),
    # per-leaf gather around compute (scan body: one site per stacked
    # leaf), reduce-scatter transposes, one loss mean (no barrier)
    "fsdp": CollectiveContract(
        "fsdp", ("dp",),
        lambda c: {"all_reduce": 1,
                   "all_gather": c.n_leaves,
                   "reduce_scatter": c.n_leaves},
        allows_full_param_gather=True,
        payload_bytes=lambda c: 3 * c.param_bytes,
        description="one gather + one reduce-scatter site per param leaf "
                    "(scan collapses depth), one loss pmean"),
    # fsdp with --offload opt: identical collective choreography to fsdp
    # (the transfers are custom calls, not collectives) PLUS a declared
    # host-offload transfer budget — MoveToDevice streams the Adam
    # moments in for the update, MoveToHost parks them back.  Counts
    # come from the build's OffloadPlan (zero on backends without a
    # pinned_host space: the fallback step must stay transfer-free).
    "fsdp_offload": CollectiveContract(
        "fsdp_offload", ("dp",),
        lambda c: {"all_reduce": 1,
                   "all_gather": c.n_leaves,
                   "reduce_scatter": c.n_leaves},
        allows_full_param_gather=True,
        payload_bytes=lambda c: 3 * c.param_bytes,
        host_transfers=_offload_host_transfers,
        description="fsdp choreography + declared MoveToHost/MoveToDevice "
                    "streaming of host-resident optimizer state"),
    # fsdp with matmul_precision=fp8: the e4m3/e5m2 scaled matmuls live
    # entirely inside the dense seam — the WIRE choreography is exactly
    # fsdp's (the precision leg changes flops and working set, not
    # collectives), which is precisely what this contract pins down
    "fsdp_fp8": CollectiveContract(
        "fsdp_fp8", ("dp",),
        lambda c: {"all_reduce": 1,
                   "all_gather": c.n_leaves,
                   "reduce_scatter": c.n_leaves},
        allows_full_param_gather=True,
        payload_bytes=lambda c: 3 * c.param_bytes,
        description="fsdp choreography unchanged: fp8 scaling is local "
                    "to the dense seam, any site delta is a leak"),
    # fsdp with --overlap ring_fused_pallas: projection leaves fused
    # into collective matmuls with the Pallas chunk-matmul engine — the
    # ppermute hops stay at the XLA level (CPU interpret has no remote
    # DMA), so the wire counts match the fused choreography, not the
    # kernel impl
    "fsdp_ring_fused_pallas": CollectiveContract(
        "fsdp_ring_fused_pallas", ("dp",),
        _fsdp_ring_fused_pallas_counts,
        allows_full_param_gather=True,
        payload_bytes=lambda c: 3 * c.param_bytes,
        description="7 projection leaves as fused ring matmuls (fwd + "
                    "bwd hop rings, no gather/scatter sites), plain "
                    "ring + psum_scatter for the rest, one loss pmean"),
    # fsdp with --overlap ring: the overlap engine's decomposed gathers
    # (ops.collectives.ring_all_gather) — ppermute hops instead of
    # monolithic all_gathers, bitwise-identical losses
    "fsdp_ring": CollectiveContract(
        "fsdp_ring", ("dp",), _fsdp_ring_counts,
        allows_full_param_gather=True,
        payload_bytes=lambda c: 3 * c.param_bytes,
        description="(ws-1) ppermute hops per gathered leaf, monolithic "
                    "psum_scatter backward per leaf, one loss pmean; "
                    "any all_gather site is a fallback to the "
                    "un-decomposed path"),
    # tp with --overlap ring: the two per-layer rejoin psums decomposed
    # into psum_scatter + ring all-gather (bitwise-identical)
    "tp_ring": CollectiveContract(
        "tp_ring", ("dp", "tp"), _tp_ring_counts,
        payload_bytes=None,
        description="2 rejoin psum_scatter sites + 2(tp-1) ppermute hops "
                    "+ per-leaf grad psums; gather/scatter of params "
                    "still forbidden"),
    # tp with --overlap q8: rejoin psums ride the wire as int8 codes +
    # scales (EQuARX two-shot, arXiv:2506.17615) — all_gather sites over
    # tp replace the 2 rejoin all_reduce sites; grads stay full-precision
    "tp_q8": CollectiveContract(
        "tp_q8", ("dp", "tp"), _tp_q8_counts,
        # two rejoins/layer-site ship int8 + f32-scale instead of f32:
        # ~4x fewer activation bus bytes (informational; activation
        # payloads aren't param-tree-derivable, so no estimate)
        payload_bytes=None,
        description="4 all_gather sites (codes + scales per rejoin) + "
                    "full-precision grad/backward psums; gather of "
                    "params still forbidden"),
    # Megatron TP: activations psum'd in the layer body (2/layer-site),
    # grads psum'd per replicated leaf; NO param gathers or scatters —
    # an all_gather here means a param silently went dp-replicated.
    "tp": CollectiveContract(
        "tp", ("dp", "tp"),
        lambda c: {"all_reduce": (c.n_leaves + 2, c.n_leaves + 8)},
        payload_bytes=None,
        description="activation psums + per-leaf grad psums only; any "
                    "gather/scatter site is a choreography break"),
    # FSDP over dp × ring attention over sp: fsdp sites + the KV ring's
    # collective_permutes (k and v, forward + backward = 4 sites) + per-
    # leaf sp grad psums (params are sp-replicated)
    "sp": CollectiveContract(
        "sp", ("dp", "sp"),
        lambda c: {"all_reduce": c.n_leaves + 2,
                   "all_gather": c.n_leaves,
                   "reduce_scatter": c.n_leaves,
                   "collective_permute": 4},
        allows_full_param_gather=True,
        payload_bytes=None,
        description="fsdp choreography + 4 KV-ring ppermute sites + sp "
                    "grad psums"),
    # switch-MoE: a2a dispatch + return in the scanned layer body, each
    # with its backward transpose (4 sites); dense/router grads psum'd
    "moe": CollectiveContract(
        "moe", ("dp", "ep"),
        lambda c: {"all_reduce": (c.n_leaves + 2, c.n_leaves + 8),
                   "all_to_all": 4},
        payload_bytes=None,
        description="4 all_to_all sites (dispatch/return × fwd/bwd) + "
                    "per-leaf grad psums; gathers/scatters forbidden"),
    # serving decode (serving.engine.make_serve_decode_step under tp):
    # inference-only, so the whole choreography is the layer body's two
    # rejoin psums — and the layer stack is UNROLLED (static layer index
    # into the per-layer KV pools), so the sites scale with depth instead
    # of collapsing like the scanned train steps.  Params stay sharded at
    # rest: any gather/scatter site means a weight went replicated, and
    # any dp-axis collective means requests leaked across slots.
    "serve_decode": CollectiveContract(
        "serve_decode", ("tp",),
        lambda c: {"all_reduce": 2 * c.n_layers},
        payload_bytes=None,
        description="2 activation psums per (unrolled) layer over tp "
                    "only; no grads, so no other collective may appear"),
    # serve_decode with the Pallas paged-attention kernel: attention
    # reads KV pages in place inside the kernel — pure local compute,
    # so the wire choreography is bitwise serve_decode's
    "serve_decode_paged_kernel": CollectiveContract(
        "serve_decode_paged_kernel", ("tp",),
        lambda c: {"all_reduce": 2 * c.n_layers},
        payload_bytes=None,
        description="2 activation psums per (unrolled) layer over tp "
                    "only; the paged kernel adds zero wire sites"),
    # speculative verify (serving.engine.make_serve_spec_verify_step):
    # one (B, k+1) target forward replacing k+1 sequential decode steps
    # — batching over S is slot-local compute, so the choreography is
    # bitwise serve_decode's (verification is per-row argmax; the
    # accept/rollback arithmetic runs in a separate collective-free jit)
    "serve_decode_spec": CollectiveContract(
        "serve_decode_spec", ("tp",),
        lambda c: {"all_reduce": 2 * c.n_layers},
        payload_bytes=None,
        description="2 activation psums per (unrolled) layer over tp "
                    "only; the (B, k+1) verify batch adds zero wire "
                    "sites"),
    # batched flash prefill (serving.engine.make_serve_prefill_batch_
    # step): the chunk's attention runs inside the Pallas flash kernel
    # — pages read in place, online softmax local to the shard's heads
    # — so again only the layer body's two rejoin psums hit the wire
    "serve_prefill_flash": CollectiveContract(
        "serve_prefill_flash", ("tp",),
        lambda c: {"all_reduce": 2 * c.n_layers},
        payload_bytes=None,
        description="2 activation psums per (unrolled) layer over tp "
                    "only; the flash prefill kernel adds zero wire "
                    "sites"),
    # pipeline stages are single-device jitted programs; inter-stage comm
    # is host-mediated device transfer, never a mesh collective
    "gpipe": CollectiveContract(
        "gpipe", (), lambda c: {},
        description="stage programs carry zero collectives"),
    "1f1b": CollectiveContract(
        "1f1b", (), lambda c: {},
        description="stage programs carry zero collectives"),
}


# ---------------------------------------------------------------- checking

def parse_expected_spec(value) -> tuple[int, float]:
    """One value of a serialized verdict's ``expected`` dict
    (``ContractVerdict.to_dict``: int exact, ``"lo..hi"`` range,
    ``"any"``/None unchecked) -> an inclusive ``(lo, hi)`` bound.  The
    measured-side consumers (``telemetry.ledger``'s trace join) re-check
    ranges from the manifest's already-serialized verdict, so the parse
    lives next to the serializer."""
    if value is None or value == "any":
        return 0, math.inf
    if isinstance(value, str) and ".." in value:
        lo, hi = value.split("..", 1)
        return int(lo), int(hi)
    if isinstance(value, (tuple, list)):
        return int(value[0]), int(value[1])
    return int(value), int(value)


@dataclass
class ContractVerdict:
    """Outcome of checking observed counts against one contract."""
    strategy: str
    ok: bool
    expected: dict
    observed: dict
    violations: list[str]
    payload_bytes: int | None = None

    def summary(self) -> str:
        if self.ok:
            seen = ", ".join(f"{k}={v}" for k, v in
                             sorted(self.observed.items()) if v)
            return f"OK ({seen})" if seen else "OK (no collectives)"
        return "VIOLATED: " + "; ".join(self.violations)

    def to_dict(self) -> dict:
        return {"strategy": self.strategy, "ok": self.ok,
                "expected": self.expected, "observed": self.observed,
                "violations": self.violations,
                "payload_bytes": self.payload_bytes}


def check_counts(contract: CollectiveContract, observed: Mapping[str, int],
                 ctx: ContractContext) -> ContractVerdict:
    """Compare ``count_collectives``-style observed counts against the
    contract's expectation for ``ctx``.  Kinds the contract omits must be
    0; int expectations are exact; ``(lo, hi)`` inclusive; None skipped."""
    expected = dict(contract.counts(ctx))
    violations = []
    exp_out = {}
    for kind in KINDS:
        want = expected.get(kind, 0)
        got = int(observed.get(kind, 0))
        if want is None:
            exp_out[kind] = "any"
            continue
        if isinstance(want, tuple):
            lo, hi = want
            exp_out[kind] = f"{lo}..{hi}"
            if not lo <= got <= hi:
                violations.append(
                    f"{kind}: {got} sites, contract allows {lo}..{hi}")
        else:
            exp_out[kind] = int(want)
            if got != want:
                violations.append(
                    f"{kind}: {got} sites, contract expects {want}")
    payload = (int(contract.payload_bytes(ctx))
               if contract.payload_bytes else None)
    obs = {k: int(observed.get(k, 0)) for k in KINDS}
    return ContractVerdict(strategy=contract.strategy,
                           ok=not violations, expected=exp_out,
                           observed=obs, violations=violations,
                           payload_bytes=payload)


def evaluate_contract(strategy: str, observed: Mapping[str, int], *,
                      params=None, mesh=None, n_layers: int = 0,
                      ctx: ContractContext | None = None,
                      **extra) -> ContractVerdict:
    """One-call form the strategy scripts use: look up the registry,
    capture a context from the live params/mesh, check the counts they
    already computed for their startup print."""
    if strategy not in CONTRACTS:
        raise KeyError(f"no contract registered for {strategy!r}; "
                       f"have {sorted(CONTRACTS)}")
    if ctx is None:
        ctx = ContractContext.capture(params=params, mesh=mesh,
                                      n_layers=n_layers, **extra)
    return check_counts(CONTRACTS[strategy], observed, ctx)
