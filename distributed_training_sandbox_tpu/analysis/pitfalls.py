"""AST-level lint for the classic JAX training-script pitfalls.

Static, import-free (pure ``ast`` — linting a script never executes or
traces it), tuned so the current ``scripts/`` tree is clean at the
``error`` level.  Three checks:

  * ``hot-op-in-loop`` (warn) — a compute-heavy ``jnp.*`` / ``jax.nn.*``
    call inside a Python ``for``/``while`` body in a function that isn't
    jit-decorated: each iteration dispatches ops eagerly (op-by-op on
    device, retrace-free but orders of magnitude off a fused step).
    Data-movement calls (``asarray``/``array``/``zeros``…) are exempt —
    host->device staging in the step loop is the normal pattern.
  * ``collective-outside-shard-map`` (error) — the file calls axis
    collectives (``lax.psum`` family / the ``ops.collectives`` wrappers)
    but never references ``shard_map``/``smap``/``pmap``: the axis name
    can't be bound, so the script either crashes at trace time or — the
    nastier variant — someone "fixes" it by removing the axis and the
    reduction silently disappears.
  * ``step-jit-missing-donation`` (warn) — ``jax.jit(...)`` bound to a
    ``*step*`` name without ``donate_argnums``: params + optimizer state
    are double-buffered every step.
  * ``host-sync-in-loop`` — a per-step host synchronization inside a
    Python loop outside jit: ``jax.block_until_ready``/``local_scalar``
    (error), or ``float(<...loss...>)`` (warn).  This is the old
    synchronous-driver pattern the ``runtime`` step pump replaces —
    route the loop through ``StepPump`` so the host only blocks at the
    declared sync policy points.  Loops that sync *deliberately*
    (latency benchmarks, warmup fences) mark the line — or the line
    above it — with a ``sync-ok`` comment to suppress the finding.
  * ``ckpt-manager-no-wait`` (error) — the file opens an Orbax manager
    (``checkpoint_manager(...)`` / ``CheckpointManager(...)``) but never
    guarantees ``wait_until_finished`` on exit: no direct call, no
    ``utils.checkpoint.closing(...)`` wrapper, no ``resilience``
    ``Checkpointer``/``Supervisor`` (both close in a finally).  An async
    ``save_state(..., wait=False)`` then races process exit and can
    leave a torn newest step.  A deliberate open (restore-only paths
    that never save) marks the call line — or the line above — with a
    ``ckpt-ok`` comment.

  * ``swallowed-distributed-error`` (error) — a bare ``except
    Exception: pass`` (or ``continue``, or bare ``except:``) whose
    ``try`` body runs collective or ``*step*`` calls: swallowed
    distributed errors are how hangs become silent — the rank that ate
    the exception stops participating and every peer wedges in the next
    collective with no diagnosis.  Handlers that *do* something (log,
    re-raise, return a fallback) are fine; a deliberate swallow marks
    the ``except`` line — or the line above — with ``# swallow-ok``.

  * ``gather-in-step`` (error) — a monolithic ``all_gather`` inside a
    ``*step*`` function in a module that also has a ring variant in
    scope (``ring_all_gather`` / ``all_gather_matmul``): the overlap
    engine exists precisely so hot-path gathers decompose into
    schedulable ppermute hops; a plain all_gather next to an available
    ring twin is usually a missed ``overlap="ring"`` wiring, not a
    choice.  A deliberate monolithic gather (e.g. the baseline leg of
    an A/B) marks the line — or the line above — with ``# gather-ok``.

  * ``pallas-call-no-interpret`` (error) — a ``pl.pallas_call(...)``
    site in library code with no ``interpret=`` argument: the kernel
    would compile for whatever backend is active, so the CPU tier
    (every test and fixture here) crashes instead of interpreting.
    Every kernel wrapper must plumb an ``interpret`` knob (the repo
    convention: default ``jax.default_backend() != "tpu"``).  A site
    that forwards ``**kwargs`` is accepted; a deliberate compile-only
    call marks the line — or the line above — with ``# pallas-ok``.

  * ``span-name-not-static`` (error) — a span/metric emit site
    (``maybe_span`` / ``spans.span`` / ``spans.record`` /
    ``metrics.inc|set|observe`` and their ``maybe_*`` guards) whose
    name argument is not a static string literal: an f-string or
    concatenation mints a new series per distinct value — unbounded
    cardinality that bloats the Prometheus endpoint and shatters the
    timeline into one-off tracks.  Keep the name static and put the
    variation in attrs/labels.  A call site whose dynamic name draws
    from a provably closed set marks the call line — or the line above
    — with ``# span-ok``.

  * ``hand-rolled-partition-spec`` (error) — a non-trivial
    ``PartitionSpec``/``P``/``PS`` literal inside a ``*step*`` function
    of a module whose strategies are covered by a partition RuleSet
    (``rules.RULE_COVERED_MODULE_STEMS``): placement there is supposed
    to *derive* from the rules — a hand-rolled literal is exactly the
    drift the ``--rules`` lint exists to catch, one refactor earlier.
    The step makers' own in/out specs (the seam where rules become
    shardings) mark the line — or the line above — with ``# spec-ok``.

  * ``mem-stats-in-hot-loop`` (warn) — ``memory_stats()`` /
    ``device_memory_stats()`` inside a Python loop of a ``*step*``
    function: the allocator query is a host round-trip, so polling it
    per iteration is a host-sync landmine (the exact pattern
    ``PerformanceTracker`` replaced with guarded sampling).  Route the
    read through ``telemetry.memledger.get_sampler()`` — or any
    every-N/finalize-only guard — and mark a deliberate per-iteration
    poll with ``# mem-ok``.

  * ``wall-clock-in-sim`` (error, OPT-IN) — a wall-clock read
    (``time.time()`` / ``perf_counter()`` / ``monotonic()`` and their
    ``_ns`` twins) in a module that is supposed to run under the fleet
    simulator's virtual clock (``sim/`` and the sim-clocked serving
    schedulers): one stray wall read makes a "deterministic" replay
    drift with host load, which is exactly the bug class the virtual
    clock exists to kill.  Real-time drivers inside those trees (the
    live engine's measured-latency stamps) mark the line — or the line
    above — with ``# clock-ok``.  This check is NOT in the default
    set — ``lint_tree(..., opt_in={"wall-clock-in-sim"})`` enables it
    for the swept trees only, since scripts and the rest of the
    package legitimately read wall clock.

Findings carry a severity; ``scripts/lint_sharding.py`` fails the run
only on errors (``--strict`` promotes warnings).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from pathlib import Path

HOT_OPS = {
    "dot", "matmul", "einsum", "tensordot", "exp", "log", "log2",
    "softmax", "logsumexp", "mean", "sum", "prod", "var", "std",
    "tanh", "sqrt", "square", "power", "cumsum", "sort", "argsort",
    "take_along_axis", "relu", "gelu", "silu", "sigmoid",
}
DATA_MOVEMENT_OPS = {
    "asarray", "array", "zeros", "ones", "full", "arange", "zeros_like",
    "ones_like", "stack", "concatenate", "pad", "reshape", "split",
}
COLLECTIVE_FNS = {
    "psum", "pmax", "pmin", "pmean", "psum_scatter", "all_gather",
    "ppermute", "all_to_all", "axis_index", "all_reduce",
    "reduce_scatter", "broadcast", "tree_all_reduce", "tree_all_gather",
    "ppermute_ring", "barrier",
}
SHARD_WRAPPERS = {"shard_map", "smap", "pmap", "shmap", "xmap"}
# per-step host synchronization calls — the pattern the runtime step
# pump's sync policy replaces in driver hot loops
HOST_SYNC_FNS = {"block_until_ready", "local_scalar"}
# allocator-stats queries (each one a device round-trip) — polling them
# inside a *step* hot loop is the pattern the memory ledger's shared
# sampler replaces
MEM_STATS_FNS = {"memory_stats", "device_memory_stats"}
# opening an Orbax manager; and the names whose presence anywhere in the
# file counts as a guaranteed wait_until_finished-on-exit
CKPT_OPENERS = {"checkpoint_manager", "CheckpointManager"}
CKPT_GUARDS = {"wait_until_finished", "closing", "Checkpointer",
               "Supervisor"}
# names whose presence anywhere in the file means a ring-decomposed
# gather is available — a monolithic all_gather in a *step* function is
# then flagged (the overlap-engine wiring lint)
RING_VARIANTS = {"ring_all_gather", "all_gather_matmul"}
# wall-clock reads forbidden in sim-clocked modules (the opt-in
# wall-clock-in-sim check); matched as time.<fn>() or a bare <fn>()
# from-import
WALL_CLOCK_FNS = {"time", "perf_counter", "monotonic", "time_ns",
                  "perf_counter_ns", "monotonic_ns"}
# checks that never fire unless a caller opts a tree in
OPT_IN_CHECKS = {"wall-clock-in-sim"}

SEV_ERROR = "error"
SEV_WARN = "warn"


@dataclass
class PitfallFinding:
    path: str
    line: int
    check: str
    severity: str
    message: str

    def to_dict(self) -> dict:
        return {"path": self.path, "line": self.line, "check": self.check,
                "severity": self.severity, "message": self.message}


def _attr_chain(node: ast.AST) -> str:
    """Dotted name of an attribute/name expression ('' if not one)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _is_jit_call(call: ast.Call) -> bool:
    chain = _attr_chain(call.func)
    return chain.endswith("jit") and "jit" in chain.split(".")


def _has_jit_decorator(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        if "jit" in _attr_chain(target):
            return True
        # functools.partial(jax.jit, ...) style
        if isinstance(dec, ast.Call) and any(
                "jit" in _attr_chain(a) for a in dec.args):
            return True
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: list[PitfallFinding] = []
        self._loop_depth = 0
        self._jit_depth = 0
        self._fn_stack: list[str] = []
        self.uses_shard_wrapper = False
        self.collective_calls: list[tuple[int, str]] = []
        self.ckpt_opens: list[tuple[int, str]] = []
        self.has_ckpt_guard = False
        self.has_ring_variant = False
        self.gathers_in_step: list[tuple[int, str]] = []
        self.swallowed: list[tuple[int, str]] = []
        self.dynamic_emit_names: list[tuple[int, str]] = []
        self.pallas_no_interpret: list[tuple[int, str]] = []
        self.mem_stats_in_loop: list[tuple[int, str]] = []
        self.spec_literals: list[tuple[int, str]] = []
        self.wall_clock_calls: list[tuple[int, str]] = []

    # -- context tracking -------------------------------------------------
    def _visit_function(self, node):
        jitted = _has_jit_decorator(node)
        self._jit_depth += jitted
        self._fn_stack.append(node.name)
        # a nested function starts a fresh loop context: a closure built
        # inside a loop body does not itself run per-iteration
        saved, self._loop_depth = self._loop_depth, 0
        self.generic_visit(node)
        self._loop_depth = saved
        self._fn_stack.pop()
        self._jit_depth -= jitted

    visit_FunctionDef = visit_AsyncFunctionDef = _visit_function

    def _visit_import(self, node):
        for alias in node.names:
            if alias.name.rsplit(".", 1)[-1] in RING_VARIANTS \
                    or (alias.asname or "") in RING_VARIANTS:
                self.has_ring_variant = True

    visit_Import = visit_ImportFrom = _visit_import

    def _visit_loop(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = visit_While = _visit_loop

    def visit_Try(self, node):
        """The swallowed-distributed-error check: a handler that
        catches everything and does nothing, wrapped around collective
        or ``*step*`` calls."""
        risky = self._distributed_call_in(node.body)
        if risky:
            for h in node.handlers:
                if not _catches_everything(h) or not _body_is_noop(h.body):
                    continue
                self.swallowed.append((h.lineno, risky))
        self.generic_visit(node)

    def _distributed_call_in(self, body) -> str:
        """Dotted name of the first collective / *step* call under
        ``body`` ('' if none)."""
        for stmt in body:
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Call):
                    continue
                chain = _attr_chain(sub.func)
                leaf = chain.rsplit(".", 1)[-1]
                if leaf in COLLECTIVE_FNS or "step" in leaf.lower():
                    return chain or leaf
        return ""

    # -- checks -----------------------------------------------------------
    def visit_Call(self, node: ast.Call):
        chain = _attr_chain(node.func)
        leaf = chain.rsplit(".", 1)[-1]
        root = chain.split(".", 1)[0]
        if leaf in SHARD_WRAPPERS or root in SHARD_WRAPPERS:
            self.uses_shard_wrapper = True
        if (self._loop_depth and not self._jit_depth
                and root in ("jnp", "jax")
                and leaf in HOT_OPS and leaf not in DATA_MOVEMENT_OPS):
            self.findings.append(PitfallFinding(
                self.path, node.lineno, "hot-op-in-loop", SEV_WARN,
                f"{chain}() inside a Python loop outside jit — each "
                f"iteration dispatches eagerly; move the loop body into "
                f"a jitted step (or lax.scan)"))
        if (leaf in COLLECTIVE_FNS
                and root in ("lax", "jax", "C", "collectives")):
            self.collective_calls.append((node.lineno, chain))
            if (leaf == "all_gather"
                    and any("step" in n.lower() for n in self._fn_stack)):
                self.gathers_in_step.append((node.lineno, chain))
        if leaf in RING_VARIANTS:
            self.has_ring_variant = True
        if leaf == "pallas_call":
            # interpret= may arrive positionally never (keyword-only in
            # pallas), via an explicit keyword, or through **kwargs
            kw = {k.arg for k in node.keywords}
            if "interpret" not in kw and None not in kw:
                self.pallas_no_interpret.append((node.lineno,
                                                 chain or leaf))
        if leaf in CKPT_OPENERS:
            self.ckpt_opens.append((node.lineno, chain))
        if leaf in CKPT_GUARDS:
            self.has_ckpt_guard = True
        if (leaf == "PartitionSpec"
                or (isinstance(node.func, ast.Name)
                    and node.func.id in ("P", "PS"))):
            # a spec literal that actually partitions something (any
            # non-None entry) inside a *step* function — replicated P()
            # and the None placeholders are not placement decisions
            nontrivial = bool(node.keywords) or any(
                not (isinstance(a, ast.Constant) and a.value is None)
                for a in node.args)
            if nontrivial and any("step" in n.lower()
                                  for n in self._fn_stack):
                self.spec_literals.append((node.lineno, chain or leaf))
        if leaf in WALL_CLOCK_FNS and root in ("time", leaf):
            # time.time() / time.perf_counter() / a bare from-import —
            # only reported when the tree opted into wall-clock-in-sim
            self.wall_clock_calls.append((node.lineno, chain or leaf))
        if (leaf in MEM_STATS_FNS and self._loop_depth
                and not self._jit_depth
                and any("step" in n.lower() for n in self._fn_stack)):
            self.mem_stats_in_loop.append((node.lineno, chain or leaf))
        if self._loop_depth and not self._jit_depth:
            self._check_host_sync(node, chain, leaf, root)
        if _is_jit_call(node):
            self._check_donation(node)
        self._check_emit_name(node, chain, leaf)
        self.generic_visit(node)

    def _check_emit_name(self, node: ast.Call, chain: str,
                         leaf: str) -> None:
        """The span-name-not-static check: find the name argument of a
        telemetry emit call and require a string literal."""
        low = chain.lower()
        if leaf in ("maybe_span", "maybe_inc", "maybe_set",
                    "maybe_observe"):
            idx = 1          # (stream_or_registry, name, ...)
        elif leaf == "span" and isinstance(node.func, ast.Attribute):
            idx = 0          # <spans>.span(name, ...)
        elif leaf == "record" and "span" in low:
            idx = 0          # <spans>.record(name, ...)
        elif leaf in ("inc", "set", "observe") and "metric" in low:
            idx = 0          # <metrics>.inc/set/observe(name, ...)
        else:
            return
        name_arg = node.args[idx] if len(node.args) > idx else next(
            (k.value for k in node.keywords if k.arg == "name"), None)
        if name_arg is None:
            return
        if isinstance(name_arg, ast.Constant) \
                and isinstance(name_arg.value, str):
            return
        self.dynamic_emit_names.append((node.lineno, chain or leaf))

    def _check_host_sync(self, node: ast.Call, chain: str, leaf: str,
                         root: str) -> None:
        """The old synchronous hot-loop shape: a blocking host round-trip
        every iteration.  Severity: error for the explicit fences
        (block_until_ready / local_scalar), warn for float(<loss>)."""
        if leaf in HOST_SYNC_FNS and root in ("jax", leaf):
            self.findings.append(PitfallFinding(
                self.path, node.lineno, "host-sync-in-loop", SEV_ERROR,
                f"{chain}() inside a Python loop — a host sync every "
                f"step; route the loop through runtime.StepPump's sync "
                f"policy (or mark a deliberate sync with '# sync-ok')"))
            return
        if (isinstance(node.func, ast.Name) and node.func.id == "float"
                and node.args):
            arg = _attr_chain(node.args[0])
            if "loss" in arg.lower():
                self.findings.append(PitfallFinding(
                    self.path, node.lineno, "host-sync-in-loop", SEV_WARN,
                    f"float({arg}) inside a Python loop forces a device "
                    f"round-trip per step; let the step pump resolve "
                    f"losses at its sync points"))

    def visit_Name(self, node: ast.Name):
        if node.id in SHARD_WRAPPERS:
            self.uses_shard_wrapper = True
        if node.id in CKPT_GUARDS:
            self.has_ckpt_guard = True
        if node.id in RING_VARIANTS:
            self.has_ring_variant = True

    def visit_Attribute(self, node: ast.Attribute):
        if node.attr in SHARD_WRAPPERS:
            self.uses_shard_wrapper = True
        if node.attr in CKPT_GUARDS:
            self.has_ckpt_guard = True
        if node.attr in RING_VARIANTS:
            self.has_ring_variant = True
        self.generic_visit(node)

    def _check_donation(self, node: ast.Call):
        kw = {k.arg for k in node.keywords}
        if kw & {"donate_argnums", "donate_argnames"}:
            return
        parent = getattr(node, "_assigned_name", None)
        if parent and "step" in parent.lower():
            self.findings.append(PitfallFinding(
                self.path, node.lineno, "step-jit-missing-donation",
                SEV_WARN,
                f"jax.jit bound to {parent!r} without donate_argnums — "
                f"params/opt-state are double-buffered every step"))


def _catches_everything(handler: ast.ExceptHandler) -> bool:
    """bare ``except:`` or ``except Exception/BaseException``."""
    if handler.type is None:
        return True
    name = _attr_chain(handler.type).rsplit(".", 1)[-1]
    return name in ("Exception", "BaseException")


def _body_is_noop(body) -> bool:
    """Only ``pass``/``continue`` (docstring-style bare constants too) —
    the handler observes the failure and discards it."""
    return all(isinstance(s, (ast.Pass, ast.Continue))
               or (isinstance(s, ast.Expr)
                   and isinstance(s.value, ast.Constant))
               for s in body)


def _annotate_assignments(tree: ast.AST) -> None:
    """Tag each Call node with the simple name it's assigned to (for the
    donation check's '*step*' heuristic)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    node.value._assigned_name = t.id


def lint_source(src: str, path: str = "<string>", *,
                opt_in: set[str] | None = None) -> list[PitfallFinding]:
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [PitfallFinding(path, e.lineno or 0, "syntax", SEV_ERROR,
                               f"not parseable: {e.msg}")]
    _annotate_assignments(tree)
    v = _Visitor(path)
    v.visit(tree)
    # pragmas: a marker on the flagged line or the line above suppresses
    # exactly its check — 'sync-ok' for deliberate per-iteration syncs
    # (latency bench, warmup fence), 'ckpt-ok' for deliberate unguarded
    # manager opens (restore-only paths) — nothing else
    lines = src.splitlines()
    def _pragma(line_no: int, marker: str) -> bool:
        return any(marker in lines[i]
                   for i in (line_no - 1, line_no - 2)
                   if 0 <= i < len(lines))
    findings = [f for f in v.findings
                if not (f.check == "host-sync-in-loop"
                        and _pragma(f.line, "sync-ok"))]
    if v.ckpt_opens and not v.has_ckpt_guard:
        for line, chain in v.ckpt_opens:
            if _pragma(line, "ckpt-ok"):
                continue
            findings.append(PitfallFinding(
                path, line, "ckpt-manager-no-wait", SEV_ERROR,
                f"{chain}() opened but the file never guarantees "
                f"wait_until_finished() on exit — an async save racing "
                f"process exit can leave a torn newest step; wrap the "
                f"manager in utils.checkpoint.closing(...) (or use "
                f"resilience.Checkpointer), or mark a restore-only "
                f"open with '# ckpt-ok'"))
    for line, chain in v.swallowed:
        if _pragma(line, "swallow-ok"):
            continue
        findings.append(PitfallFinding(
            path, line, "swallowed-distributed-error", SEV_ERROR,
            f"except-and-discard around {chain}() — a swallowed "
            f"distributed error turns into a silent hang: the rank "
            f"that ate it stops participating and every peer wedges "
            f"in the next collective; handle or re-raise (or mark a "
            f"deliberate swallow with '# swallow-ok')"))
    if v.has_ring_variant:
        for line, chain in v.gathers_in_step:
            if _pragma(line, "gather-ok"):
                continue
            findings.append(PitfallFinding(
                path, line, "gather-in-step", SEV_ERROR,
                f"{chain}() inside a *step* function while a ring "
                f"variant (ring_all_gather / all_gather_matmul) is in "
                f"scope in this module — decompose the hot-path gather "
                f"(overlap='ring') so its hops can hide behind compute, "
                f"or mark a deliberate monolithic gather with "
                f"'# gather-ok'"))
    for line, chain in v.pallas_no_interpret:
        if _pragma(line, "pallas-ok"):
            continue
        findings.append(PitfallFinding(
            path, line, "pallas-call-no-interpret", SEV_ERROR,
            f"{chain}() without an interpret= argument — the kernel "
            f"hard-compiles for the active backend and the CPU tier "
            f"cannot run it; plumb an interpret knob through the "
            f"wrapper (default jax.default_backend() != 'tpu'), or "
            f"mark a deliberate compile-only site with '# pallas-ok'"))
    from .rules import RULE_COVERED_MODULE_STEMS
    if Path(path).stem in RULE_COVERED_MODULE_STEMS:
        for line, chain in v.spec_literals:
            if _pragma(line, "spec-ok"):
                continue
            findings.append(PitfallFinding(
                path, line, "hand-rolled-partition-spec", SEV_ERROR,
                f"{chain}(...) literal inside a *step* function of a "
                f"rule-covered module — placement here must derive from "
                f"the strategy's RuleSet (analysis.rules), not a "
                f"hand-rolled spec the --rules drift lint can't see "
                f"coming; derive it, or mark the step maker's "
                f"rules-derived seam with '# spec-ok'"))
    for line, chain in v.mem_stats_in_loop:
        if _pragma(line, "mem-ok"):
            continue
        findings.append(PitfallFinding(
            path, line, "mem-stats-in-hot-loop", SEV_WARN,
            f"{chain}() inside a *step* hot loop — each allocator query "
            f"is a host round-trip; sample through the memory ledger's "
            f"shared sampler (telemetry.memledger.get_sampler) or an "
            f"every-N guard, or mark a deliberate per-iteration poll "
            f"with '# mem-ok'"))
    for line, chain in v.dynamic_emit_names:
        if _pragma(line, "span-ok"):
            continue
        findings.append(PitfallFinding(
            path, line, "span-name-not-static", SEV_ERROR,
            f"{chain}() with a non-literal span/metric name — a dynamic "
            f"name mints a new series per distinct value (unbounded "
            f"cardinality); keep the name a static string and put the "
            f"variation in attrs/labels, or mark a provably-closed name "
            f"set with '# span-ok'"))
    if "wall-clock-in-sim" in (opt_in or ()):
        for line, chain in v.wall_clock_calls:
            if _pragma(line, "clock-ok"):
                continue
            findings.append(PitfallFinding(
                path, line, "wall-clock-in-sim", SEV_ERROR,
                f"{chain}() in a sim-clocked module — a wall-clock "
                f"read makes the virtual-clock replay drift with host "
                f"load; take the time from the injected clock (the "
                f"`now` the round was scheduled at), or mark a "
                f"real-time driver's measurement site with "
                f"'# clock-ok'"))
    if v.collective_calls and not v.uses_shard_wrapper:
        line, chain = v.collective_calls[0]
        findings.append(PitfallFinding(
            path, line, "collective-outside-shard-map", SEV_ERROR,
            f"{chain}() (+{len(v.collective_calls) - 1} more collective "
            f"calls) but the file never enters shard_map/pmap — the axis "
            f"name has nothing to bind to"))
    return findings


def lint_file(path, *, opt_in: set[str] | None = None
              ) -> list[PitfallFinding]:
    p = Path(path)
    return lint_source(p.read_text(), str(p), opt_in=opt_in)


def lint_tree(root, *, recursive: bool = False,
              checks: set[str] | None = None,
              opt_in: set[str] | None = None) -> list[PitfallFinding]:
    """Lint every ``*.py`` under ``root``.  Flat by default (the
    scripts/ layout); ``recursive=True`` walks a package tree.
    ``checks`` restricts the findings to those check names — the
    package tree gets only the swallowed-distributed-error check (its
    internals legitimately trip the driver-shaped heuristics, e.g.
    collective wrappers outside shard_map).  ``opt_in`` enables the
    checks in ``OPT_IN_CHECKS`` (off everywhere by default) for this
    tree — e.g. ``opt_in={"wall-clock-in-sim"}`` on the sim-clocked
    serving/sim trees."""
    findings = []
    pattern = "**/*.py" if recursive else "*.py"
    for p in sorted(Path(root).glob(pattern)):
        if "__pycache__" in p.parts:
            continue
        findings.extend(lint_file(p, opt_in=opt_in))
    if checks is not None:
        findings = [f for f in findings if f.check in checks]
    return findings
