"""Lint passes over compiled HLO text.

Five checks, each catching one way a refactor silently breaks the
sharding story without failing any numeric test:

  * **replication** — an ``all-gather`` whose output is a full-parameter
    shape means a sharded param is being materialized; for strategies
    whose contract doesn't gather params (DDP, TP, ZeRO-1/2 broadcast
    rebuild) that is a full extra copy of the weights on the wire every
    step (the automatic-weight-update-sharding failure mode, PAPERS.md);
  * **donation** — ``donate_argnums`` was requested but the compiled
    module carries no ``input_output_alias`` entries: every step then
    allocates fresh param/state buffers (2× resident memory);
  * **host transfer** — ``MoveToHost``/``MoveToDevice`` custom calls or
    ``S(5)``-space buffers inside a step function: a device→host sync
    on the hot path;
  * **foreign axis** — a collective whose replica groups match no
    declared mesh axis combination: the op spans devices the strategy
    never meant to couple (e.g. a psum leaking across ``tp`` in a
    dp-only gradient sync);
  * **sharding drift** — a compiled entry parameter whose
    ``sharding={...}`` annotation tiles a dimension differently than the
    strategy's partition-rule-derived spec says it should: a driver that
    silently diverged from its declared rules goes red statically
    (:func:`check_sharding_drift`, fed by ``rules.expected_arg_specs``).

All checks are pure text analysis over ``lowered.compile().as_text()``
— nothing executes, so they run on the CPU backend in CI against the
same programs the TPU would run (module structure is backend-portable
even though fusion details differ).
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass
from itertools import combinations

from ..ops.hlo import collective_instances, entry_parameter_shardings

SEV_ERROR = "error"
SEV_WARN = "warn"

_HOST_PATTERNS = (
    r'custom_call_target="MoveToHost"',
    r'custom_call_target="MoveToDevice"',
    r'custom_call_target="annotate_device_placement"',
    r"S\(5\)",  # host memory space in a layout annotation
)


@dataclass
class LintFinding:
    check: str          # "replication" | "donation" | "host_transfer"
    #                     | "foreign_axis"
    severity: str       # SEV_ERROR | SEV_WARN
    message: str

    def to_dict(self) -> dict:
        return {"check": self.check, "severity": self.severity,
                "message": self.message}


def param_shapes(params, *, min_numel: int = 1024) -> set:
    """The full (unsharded) shapes of a param tree, for the replication
    check.  Tiny leaves (norm scales, biases) are skipped — gathering
    those is noise, not a replication bug."""
    import jax
    return {tuple(l.shape) for l in jax.tree.leaves(params)
            if hasattr(l, "shape") and math.prod(l.shape) >= min_numel}


def mesh_axis_groupings(mesh) -> dict:
    """frozenset(axis names) -> frozenset of device-id groups for every
    non-empty axis subset of ``mesh`` — the universe of replica groups a
    collective on this mesh may legally use."""
    import numpy as np
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    names = list(mesh.axis_names)
    out = {}
    for r in range(1, len(names) + 1):
        for subset in combinations(names, r):
            axes = [names.index(a) for a in subset]
            moved = np.moveaxis(ids, axes,
                                range(ids.ndim - len(axes), ids.ndim))
            size = int(np.prod([ids.shape[a] for a in axes]))
            groups = frozenset(frozenset(int(i) for i in row)
                               for row in moved.reshape(-1, size))
            out[frozenset(subset)] = groups
    return out


# ---------------------------------------------------------------- checks

def check_replication(instances, full_param_shapes, *,
                      allow_full_param_gather: bool = False):
    if allow_full_param_gather or not full_param_shapes:
        return []
    findings = []
    for inst in instances:
        if inst.kind != "all_gather":
            continue
        hits = [s for s in inst.shapes if tuple(s) in full_param_shapes]
        for s in hits:
            findings.append(LintFinding(
                "replication", SEV_ERROR,
                f"all-gather materializes full param shape {list(s)} "
                f"({inst.bytes} B) — a sharded parameter is being "
                f"replicated every step: {inst.line[:160]}"))
    return findings


def check_donation(text: str, *, donate_expected: bool):
    if not donate_expected:
        return []
    # the alias map prints entries like "{0}: (0, {}, may-alias)" —
    # presence of any may/must-alias entry means donation took
    if re.search(r"input_output_alias=\{.*?(may|must)-alias", text):
        return []
    return [LintFinding(
        "donation", SEV_ERROR,
        "donate_argnums was requested but the compiled module has no "
        "input_output_alias entries — params/opt-state buffers are "
        "reallocated every step (2x resident memory)")]


_MOVE_PATTERNS = {
    "move_to_host": r'custom_call_target="MoveToHost"',
    "move_to_device": r'custom_call_target="MoveToDevice"',
}


def check_host_transfers(text: str, declared=None):
    """Host-transfer lint over one compiled module.

    ``declared=None`` (the default, and every strategy without an
    offload contract): ANY host-transfer marker is an error — a
    device→host sync snuck onto the hot path.

    ``declared`` = the strategy's :class:`OffloadPlan` transfer counts
    (``{"move_to_host": n | (lo, hi), "move_to_device": ...}``): the
    declared transfers are a *feature* and get count-checked instead —
    a count outside the declared range (including any transfer when the
    declaration is empty/zero, the unsupported-backend fallback) is
    still an error.  Ancillary markers (placement annotations, S(5)
    layouts) are part of a declared offload choreography and stop being
    findings only while at least one transfer is actually declared."""
    if declared is not None:
        findings = []
        expects_any = False
        for key, pat in _MOVE_PATTERNS.items():
            got = len(re.findall(pat, text))
            want = declared.get(key, 0)
            if want is None:
                expects_any = True
                continue
            lo, hi = want if isinstance(want, tuple) else (want, want)
            expects_any |= hi > 0
            if not lo <= got <= hi:
                findings.append(LintFinding(
                    "host_transfer", SEV_ERROR,
                    f"{key}: {got} transfer site(s), offload contract "
                    f"declares {lo}..{hi} — the step's host-offload "
                    f"choreography drifted from its declaration"))
        if expects_any:
            return findings
        # empty declaration (e.g. the CPU fallback build): fall through
        # to the strict scan — nothing may touch host memory spaces
    findings = []
    for pat in _HOST_PATTERNS:
        n = len(re.findall(pat, text))
        if n:
            findings.append(LintFinding(
                "host_transfer", SEV_ERROR,
                f"{n} host-transfer marker(s) matching /{pat}/ inside the "
                f"step — device->host traffic on the hot path"))
    return findings


def check_replica_axes(instances, mesh, allowed_axes=None):
    """Every collective's replica groups must equal the grouping of some
    non-empty subset of ``allowed_axes`` (default: all mesh axes).
    Unparseable groups are skipped (recorded nowhere — static analysis
    stays best-effort); singleton groups are degenerate no-ops."""
    if mesh is None:
        return []
    groupings = mesh_axis_groupings(mesh)
    legal_by_subset = {}
    allowed = (frozenset(allowed_axes) if allowed_axes is not None
               else frozenset(mesh.axis_names))
    for subset, groups in groupings.items():
        if subset <= allowed:
            legal_by_subset[groups] = subset
    findings = []
    for inst in instances:
        if inst.replica_groups is None:
            continue
        if all(len(g) <= 1 for g in inst.replica_groups):
            continue
        observed = frozenset(frozenset(g) for g in inst.replica_groups)
        if observed in legal_by_subset:
            continue
        # legal for the MESH but not for the DECLARED axes?
        over = next((subset for subset, groups in groupings.items()
                     if groups == observed), None)
        if over is not None:
            findings.append(LintFinding(
                "foreign_axis", SEV_ERROR,
                f"{inst.kind} runs over mesh axes {sorted(over)} but the "
                f"strategy declares only {sorted(allowed)}: "
                f"{inst.line[:160]}"))
        else:
            findings.append(LintFinding(
                "foreign_axis", SEV_ERROR,
                f"{inst.kind} replica groups match no mesh axis "
                f"combination of {dict(mesh.shape)}: {inst.line[:160]}"))
    return findings


def check_sharding_drift(text: str, expected, *, mesh=None,
                         axis_sizes=None):
    """Compare compiled entry-parameter ``sharding={...}`` annotations
    against the rule-derived specs, by per-dimension tile factor.

    ``expected``: the flatten-ordered :class:`rules.ExpectedLeafSpec`
    list from ``rules.expected_arg_specs`` — entry ``parameter(i)``
    order IS the jit arg flatten order, so the join is positional.
    Leaves whose role the RuleSet doesn't cover (``spec is None``, e.g.
    the serving KV pool) and parameters the compiler left unannotated
    are skipped, not failed — the check is a drift detector, not a
    completeness gate (the hygiene pass already guarantees every
    rule-covered leaf has a spec).

    Device *order* within a tile is deliberately not compared: the
    replica-group/foreign-axis check owns grouping; this check owns
    placement (which dims are cut, by how much).

    Returns ``(findings, stats)`` where ``stats`` is the JSON-ready
    verdict recorded by the CLI: checked/skipped counts + mismatches.
    """
    from .rules import spec_str, tile_dims
    if axis_sizes is None:
        axis_sizes = dict(mesh.shape) if mesh is not None else {}
    params = entry_parameter_shardings(text)
    findings = []
    stats = {"ok": True, "checked": 0, "skipped": 0,
             "entry_params": len(params), "expected_leaves": len(expected),
             "mismatches": []}
    if len(params) != len(expected):
        msg = (f"compiled module has {len(params)} entry parameters but "
               f"the step args flatten to {len(expected)} leaves — "
               f"positional join impossible, drift check skipped "
               f"(was the step lowered with dropped/extra args?)")
        findings.append(LintFinding("sharding_drift", SEV_WARN, msg))
        stats["skipped"] = len(expected)
        return findings, stats
    for leaf, param in zip(expected, params):
        ndim = len(leaf.shape)
        if leaf.spec is None or param.sharding is None or ndim == 0:
            stats["skipped"] += 1
            continue
        got = param.sharding.tiles(ndim)
        want = tile_dims(leaf.spec, ndim, axis_sizes)
        stats["checked"] += 1
        if got != want:
            stats["ok"] = False
            where = (f" (compiler op_name {param.op_name!r})"
                     if param.op_name else "")
            msg = (f"parameter({param.index}) {leaf.path} shape "
                   f"{list(leaf.shape)}: compiled sharding "
                   f"{param.sharding.raw!r} tiles dims as {list(got)}, "
                   f"but the partition rules derive "
                   f"{spec_str(leaf.spec)} = tiles {list(want)} on "
                   f"{dict(axis_sizes)}{where} — the driver drifted "
                   f"from its declared rules")
            stats["mismatches"].append(msg)
            findings.append(LintFinding("sharding_drift", SEV_ERROR, msg))
    return findings, stats


def lint_compiled_hlo(text: str, *, mesh=None, allowed_axes=None,
                      full_param_shapes=(), allow_full_param_gather=False,
                      donate_expected=False,
                      declared_host_transfers=None) -> list[LintFinding]:
    """Run every check over one compiled-HLO module text.
    ``declared_host_transfers``: the strategy contract's offload
    declaration (``CollectiveContract.host_transfers(ctx)``) — turns the
    host-transfer lint from forbid into count-check."""
    instances = collective_instances(text)
    findings = []
    findings += check_replication(
        instances, set(map(tuple, full_param_shapes)),
        allow_full_param_gather=allow_full_param_gather)
    findings += check_donation(text, donate_expected=donate_expected)
    findings += check_host_transfers(text, declared_host_transfers)
    findings += check_replica_axes(instances, mesh, allowed_axes)
    return findings
