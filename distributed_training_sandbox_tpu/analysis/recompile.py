"""Retrace/recompile detection for step functions.

The classic silent perf killer: a step function that retraces every call
(weak-typed scalars changing dtype, Python-varying shapes, a config
object failing ``__hash__`` stability) turns a 10 ms step into a
multi-second compile, and nothing *fails* — throughput just dies.  The
reference course never guards this; here it is a checkable property:
run a few steps and assert the jit cache stopped growing after the
first executed call.

Uses the jitted callable's ``_cache_size()`` (present on jax's
``PjitFunction`` since well before the pinned 0.4.x; absent attributes
degrade to ``supported=False`` rather than failing the caller).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable


def jit_cache_size(fn) -> int | None:
    """Current compilation-cache entry count of a jitted callable, or
    None when the handle doesn't expose one (not a jit wrapper)."""
    probe = getattr(fn, "_cache_size", None)
    if callable(probe):
        try:
            return int(probe())
        except Exception:
            return None
    return None


@dataclass
class RecompileReport:
    steps: int
    cache_sizes: list = field(default_factory=list)  # after each call
    supported: bool = True

    @property
    def retraces_after_settle(self) -> int:
        """New traces after step 1.  Step 0 is the expected compile;
        step 1 may legitimately retrace once when the step's outputs
        (committed, sharded) replace the host-built inputs — exactly
        what every train loop does on its first iteration.  Growth from
        step 1 onward is a real per-step recompile."""
        if len(self.cache_sizes) < 2:
            return 0
        return self.cache_sizes[-1] - self.cache_sizes[1]

    @property
    def ok(self) -> bool:
        return (not self.supported) or self.retraces_after_settle == 0

    def summary(self) -> str:
        if not self.supported:
            return "SKIPPED (no _cache_size on this callable)"
        if self.ok:
            return (f"OK (cache settled at {self.cache_sizes[-1]} "
                    f"over {self.steps} steps)"
                    if self.cache_sizes else f"OK ({self.steps} steps)")
        return (f"RECOMPILED {self.retraces_after_settle}x after step 1 "
                f"(cache sizes per step: {self.cache_sizes})")

    def to_dict(self) -> dict:
        return {"steps": self.steps, "cache_sizes": self.cache_sizes,
                "supported": self.supported, "ok": self.ok,
                "retraces_after_settle": self.retraces_after_settle}


def watch_recompiles(step_fn: Callable, args: tuple, *, n_steps: int = 4,
                     advance: Callable | None = None) -> RecompileReport:
    """Run ``step_fn(*args)`` for ``n_steps`` and report cache growth.

    ``advance(args, outputs) -> next_args`` feeds the step's outputs back
    into its inputs (required when the step donates its state buffers —
    re-calling with consumed arrays is an error).  Default: same args
    every step (safe only without donation)."""
    sizes = []
    for _ in range(max(n_steps, 2)):
        out = step_fn(*args)
        size = jit_cache_size(step_fn)
        if size is None:
            return RecompileReport(steps=len(sizes) + 1, cache_sizes=sizes,
                                   supported=False)
        sizes.append(size)
        if advance is not None:
            args = advance(args, out)
    return RecompileReport(steps=len(sizes), cache_sizes=sizes)
