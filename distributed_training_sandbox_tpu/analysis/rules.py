"""Rule-based sharding: ordered regex partition rules as source of truth.

The 20 entries of ``fixtures.STRATEGIES`` were grown as hand-registered
vertical strategies, each with a hand-calibrated
:class:`~.contracts.CollectiveContract`.  This module owns the *static*
half of the composable-core refactor (ROADMAP item 1): every strategy is
described by a :class:`RuleSet` — ordered ``(regex, spec)`` partition
rules over flattened named param paths on an arbitrary
``dp x fsdp x tp x sp x ep x pp`` mesh — from which PartitionSpecs
(params, opt-state, batch), expected collective choreographies
(:mod:`.contract_gen`) and compiled-sharding lint checks
(:func:`.hlo_lint.check_sharding_drift`) are all *derived*.

The ZeRO family is folded into a single ``weight_update_sharding``
config axis per "Automatic Cross-Replica Sharding of Weight Update"
(arXiv:2004.13336): W0 replicates the update (ddp), W1 shards optimizer
state (zero1), W2 also shards gradient reduction (zero2), W3 shards the
weights themselves at rest (zero3/fsdp) — one constructor, not four
modules' worth of contract formulas.

Rule matching is first-match-wins over ``/``-joined leaf paths (the
``match_partition_rules`` idiom of SNIPPETS.md [2]); scalars are never
partitioned.  Static rule hygiene is part of the analysis:

  * an **unmatched leaf** is an error (a param nobody placed);
  * a rule that **never matches** any leaf is a dead-rule warning;
  * an earlier rule that **fully shadows** a later one (the later rule
    hits leaves, but every hit was already claimed) is an error;
  * :meth:`MatchReport.describe` dumps which rule claimed each leaf.

Everything here is importable without jax — jax is touched only inside
the functions that walk real pytrees, so the AST lint
(:mod:`.pitfalls`) and the CLI can load the registry cheaply.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field
from typing import Any, Mapping

# A spec is a tuple of per-dimension entries: None (unsharded), one mesh
# axis name, or a tuple of axis names (e.g. ("dp", "ep") batch sharding).
Spec = tuple

# The canonical mesh axis vocabulary rules may reference.
MESH_AXES = ("dp", "fsdp", "tp", "sp", "ep", "pp")


@dataclass(frozen=True)
class Rule:
    """One ordered partition rule: leaves whose ``/``-joined path matches
    ``pattern`` (``re.search``) take ``spec``, first match wins."""
    pattern: str
    spec: Spec
    note: str = ""

    def matches(self, path: str) -> bool:
        return re.search(self.pattern, path) is not None


def spec_axes(spec: Spec) -> set:
    """Every mesh axis a spec references."""
    out = set()
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            out.update(entry)
        else:
            out.add(entry)
    return out


def to_partition_spec(spec: Spec):
    """Spec tuple -> ``jax.sharding.PartitionSpec``."""
    from jax.sharding import PartitionSpec as P  # spec-ok: the converter
    return P(*spec)


def tile_dims(spec: Spec, ndim: int, axis_sizes: Mapping[str, int]
              ) -> tuple:
    """Expected tile factor per array dimension under ``spec`` on a mesh
    with ``axis_sizes`` — the quantity compiled ``sharding={...}``
    annotations carry (``ops.hlo.ShardingAnnotation.tiles``)."""
    tiles = []
    for d in range(ndim):
        entry = spec[d] if d < len(spec) else None
        if entry is None:
            tiles.append(1)
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        tiles.append(int(math.prod(int(axis_sizes.get(a, 1))
                                   for a in axes)))
    return tuple(tiles)


def spec_str(spec: Spec) -> str:
    """Human form of a spec tuple: ``P('dp', None)``."""
    inner = ", ".join(
        "None" if e is None
        else ("(" + ",".join(repr(a) for a in e) + ")"
              if isinstance(e, (tuple, list)) else repr(e))
        for e in spec)
    return f"P({inner})"


# ---------------------------------------------------------------- paths

def _key_name(key) -> str:
    """One pytree path key -> a path segment."""
    for attr in ("key", "idx", "name"):
        v = getattr(key, attr, None)
        if v is not None:
            return str(v)
    return str(key).strip(".[]'\"")


def path_str(path) -> str:
    """A jax keypath -> the ``/``-joined form rules match against
    (``layers/wq``, ``mu/0/w``)."""
    return "/".join(_key_name(k) for k in path)


def named_leaf_paths(tree) -> list:
    """Flatten a pytree to ``[(path_str, leaf), ...]`` in flatten order —
    the named universe the rule engine matches over."""
    import jax
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(path_str(p), leaf) for p, leaf in flat]


def _leaf_shape(leaf) -> tuple:
    return tuple(getattr(leaf, "shape", ()) or ())


def _is_scalar(leaf) -> bool:
    shape = _leaf_shape(leaf)
    return len(shape) == 0 or math.prod(shape) <= 1


# ---------------------------------------------------------------- matching

@dataclass(frozen=True)
class MatchedLeaf:
    path: str
    shape: tuple
    spec: Spec
    rule_index: int          # -1 = the scalar default (never partitioned)


@dataclass
class MatchReport:
    """Outcome of matching one role's tree against one rule list, with
    the static hygiene verdicts folded in."""
    strategy: str
    role: str                              # "params" | "opt" | "batch"
    matches: list = field(default_factory=list)
    errors: list = field(default_factory=list)
    warnings: list = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.errors

    def spec_by_path(self) -> dict:
        return {m.path: m.spec for m in self.matches}

    def describe(self) -> str:
        """The rule-attribution dump: which rule claimed each leaf."""
        lines = [f"[{self.strategy}:{self.role}]"]
        for m in self.matches:
            claim = ("scalar default" if m.rule_index < 0
                     else f"rule #{m.rule_index}")
            lines.append(f"  {m.path:40s} {spec_str(m.spec):24s}"
                         f" <- {claim}")
        for w in self.warnings:
            lines.append(f"  warn: {w}")
        for e in self.errors:
            lines.append(f"  ERROR: {e}")
        return "\n".join(lines)

    def to_dict(self) -> dict:
        return {"strategy": self.strategy, "role": self.role,
                "ok": self.ok,
                "leaves": {m.path: spec_str(m.spec)
                           for m in self.matches},
                "errors": list(self.errors),
                "warnings": list(self.warnings)}


def match_partition_rules(rules, named_leaves, *, strategy: str = "",
                          role: str = "params") -> MatchReport:
    """First-match-wins rule application over ``(path, leaf)`` pairs,
    with the three hygiene checks.  ``named_leaves`` is the output of
    :func:`named_leaf_paths` (leaves may be arrays or ShapeDtypeStructs —
    only ``.shape`` is read, nothing executes)."""
    rules = tuple(rules)
    report = MatchReport(strategy=strategy, role=role)
    hits = [[] for _ in rules]       # leaves each rule's regex matches
    claims = [[] for _ in rules]     # leaves each rule actually claimed
    nonscalar = 0
    for path, leaf in named_leaves:
        if _is_scalar(leaf):
            report.matches.append(
                MatchedLeaf(path, _leaf_shape(leaf), (), -1))
            continue
        nonscalar += 1
        claimed = None
        for i, rule in enumerate(rules):
            if rule.matches(path):
                hits[i].append(path)
                if claimed is None:
                    claimed = i
                    claims[i].append(path)
        if claimed is None:
            report.errors.append(
                f"unmatched leaf {path!r} (shape "
                f"{list(_leaf_shape(leaf))}): no partition rule places "
                f"it — every non-scalar leaf must be claimed")
        else:
            report.matches.append(MatchedLeaf(
                path, _leaf_shape(leaf), rules[claimed].spec, claimed))
    # hygiene over the rule list itself — only meaningful when the tree
    # actually has leaves to claim (an empty/scalar-only tree tells us
    # nothing about the rules)
    if nonscalar:
        for i, rule in enumerate(rules):
            if not hits[i]:
                report.warnings.append(
                    f"dead rule #{i} /{rule.pattern}/ -> "
                    f"{spec_str(rule.spec)}: matches no leaf")
            elif not claims[i]:
                shadowers = sorted({
                    j for j in range(i)
                    for p in hits[i] if rules[j].matches(p)
                    and p in claims[j]})
                report.errors.append(
                    f"shadowed rule #{i} /{rule.pattern}/ -> "
                    f"{spec_str(rule.spec)}: every leaf it matches "
                    f"({', '.join(hits[i][:4])}"
                    f"{'…' if len(hits[i]) > 4 else ''}) was already "
                    f"claimed by earlier rule(s) "
                    f"{', '.join(f'#{j} /{rules[j].pattern}/' for j in shadowers)}"
                    f" — reorder or delete it")
    return report


# ---------------------------------------------------------------- rule sets

def mirror_opt_rules(param_rules) -> tuple:
    """Optimizer-state rules derived from param rules: Adam moments
    mirror the param leaf's placement (the ``mu/``/``nu/`` subtree paths
    are the param paths one level down); scalars (count) fall to the
    scalar default."""
    out = []
    for r in param_rules:
        body = r.pattern.lstrip("^")
        if body in (r".*", r".+"):
            mp = r"^(mu|nu|momentum)(/|$)"
        else:
            mp = r"^(mu|nu|momentum)/" + body
        out.append(Rule(mp, r.spec, note=f"mirrors param rule "
                                         f"/{r.pattern}/"))
    return tuple(out)


@dataclass(frozen=True)
class RuleSet:
    """The declarative source of truth for one strategy family member:
    partition rules per role plus the config knobs contract generation
    keys on.  ``weight_update_sharding`` is the W-axis of
    arXiv:2004.13336: 0 = replicated update (ddp), 1 = sharded opt
    state (zero1), 2 = + sharded grad reduction (zero2), 3 = sharded
    weights at rest (zero3 / fsdp)."""
    strategy: str
    family: str              # "data" | "fsdp" | "tp" | "sp" | "moe"
    #                          | "serve" | "pipeline"
    axes: tuple              # mesh axes the strategy's collectives span
    param_rules: tuple
    opt_rules: tuple = ()
    batch_rules: tuple = ()
    weight_update_sharding: int = 0
    config: Mapping = field(default_factory=dict)
    description: str = ""

    @property
    def arg_roles(self) -> dict:
        """Step-arg position -> role, per the fixture calling
        conventions (``fixtures.StrategyBuild.args``)."""
        if self.family == "serve":
            return {1: "params"}
        if self.family == "pipeline":
            return {0: "params"}
        return {0: "params", 1: "opt", 2: "batch"}

    def rules_for(self, role: str) -> tuple:
        return {"params": self.param_rules, "opt": self.opt_rules,
                "batch": self.batch_rules}[role]

    def match(self, role: str, tree) -> MatchReport:
        return match_partition_rules(
            self.rules_for(role), named_leaf_paths(tree),
            strategy=self.strategy, role=role)

    def partition_specs(self, tree, role: str = "params"):
        """The derived PartitionSpec pytree for ``tree`` (raises on any
        hygiene error — an unmatched leaf must not silently replicate)."""
        import jax
        report = self.match(role, tree)
        if not report.ok:
            raise ValueError(
                f"{self.strategy}:{role} rule hygiene failed:\n"
                + "\n".join(report.errors))
        by_path = report.spec_by_path()
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        specs = [to_partition_spec(by_path[path_str(p)]) for p, _ in flat]
        return jax.tree_util.tree_unflatten(treedef, specs)

    def describe(self, trees: Mapping[str, Any]) -> str:
        """Rule-attribution dump over ``{role: tree}``."""
        return "\n".join(self.match(role, tree).describe()
                         for role, tree in trees.items())


# -- constructors: one per family, the zero variants one config axis ----

def data_parallel_ruleset(strategy: str, *,
                          weight_update_sharding: int = 0,
                          grad_comm: str = "allreduce",
                          axis: str = "dp") -> RuleSet:
    """The toy-MLP data-parallel family.  ``weight_update_sharding``
    folds ddp (W0) and zero1/2/3 (W1/W2/W3) into one axis;
    ``grad_comm`` picks the W0 gradient wire format (per-leaf
    all-reduce, flat ~MB buckets, or int8-quantized buckets)."""
    w = weight_update_sharding
    if w >= 3:
        param_rules = (Rule(r".*", (axis,),
                            "W3: params at rest are flat owner chunks"),)
    else:
        param_rules = (Rule(r".*", (), "params replicated at rest"),)
    if w >= 1:
        opt_rules = (Rule(r"^(mu|nu|momentum)(/|$)", (axis,),
                          "owner-chunk optimizer moments (ZeRO)"),)
    else:
        opt_rules = mirror_opt_rules(param_rules)
    return RuleSet(
        strategy=strategy, family="data", axes=(axis,),
        param_rules=param_rules, opt_rules=opt_rules,
        batch_rules=(Rule(r".*", (axis,)),),
        weight_update_sharding=w,
        config={"grad_comm": grad_comm},
        description=f"data-parallel, weight_update_sharding=W{w}, "
                    f"grad_comm={grad_comm}")


def fsdp_ruleset(strategy: str, *, axis: str = "dp",
                 overlap: str = "none", offload: str | None = None,
                 precision: str | None = None) -> RuleSet:
    """FSDP = W3 over named leaf dims instead of flat chunks: stacked
    ``(L, ...)`` layer leaves shard dim 1 (dim 0 is the scan axis),
    plain leaves shard dim 0.  ``overlap``/``offload``/``precision``
    change wire or memory choreography, never placement."""
    param_rules = (
        Rule(r"^layers/", (None, axis),
             "stacked (L, ...) layer leaves: shard dim 1"),
        Rule(r".*", (axis,), "plain leaves (embed, final_norm): dim 0"),
    )
    return RuleSet(
        strategy=strategy, family="fsdp", axes=(axis,),
        param_rules=param_rules,
        opt_rules=mirror_opt_rules(param_rules),
        batch_rules=(Rule(r".*", (axis,)),),
        weight_update_sharding=3,
        config={"overlap": overlap, "offload": offload,
                "precision": precision},
        description=f"fsdp (W3 by named dim), overlap={overlap}"
                    + (f", offload={offload}" if offload else "")
                    + (f", precision={precision}" if precision else ""))


# Megatron column/row role split of the dense transformer projections.
TP_COL_LEAVES = ("wq", "wk", "wv", "w_gate", "w_up")
TP_ROW_LEAVES = ("wo", "w_down")


def tp_ruleset(strategy: str, *, axis: str = "tp", dp_axis: str = "dp",
               overlap: str = "none") -> RuleSet:
    """Megatron TP over stacked dense layers: column-parallel leaves
    ``(L, in, out)`` shard the out dim, row-parallel the in dim, the
    rest (embed, norms, router) replicated."""
    col = "|".join(TP_COL_LEAVES)
    row = "|".join(TP_ROW_LEAVES)
    param_rules = (
        Rule(rf"^layers/({col})$", (None, None, axis),
             "column-parallel projections: shard the out dim"),
        Rule(rf"^layers/({row})$", (None, axis, None),
             "row-parallel projections: shard the in dim"),
        Rule(r".*", (), "embed/norms replicated"),
    )
    return RuleSet(
        strategy=strategy, family="tp", axes=(dp_axis, axis),
        param_rules=param_rules,
        opt_rules=mirror_opt_rules(param_rules),
        batch_rules=(Rule(r".*", (dp_axis,)),),
        weight_update_sharding=0,
        config={"overlap": overlap},
        description=f"megatron tp, overlap={overlap}")


def sp_ruleset(strategy: str, *, axis: str = "sp",
               dp_axis: str = "dp") -> RuleSet:
    """FSDP placement over dp + ring attention over sp: params/opt are
    exactly the fsdp rules; the batch also splits its sequence dim."""
    base = fsdp_ruleset(strategy, axis=dp_axis)
    return RuleSet(
        strategy=strategy, family="sp", axes=(dp_axis, axis),
        param_rules=base.param_rules, opt_rules=base.opt_rules,
        batch_rules=(Rule(r".*", (dp_axis, axis),
                          "batch split on both dp and sequence"),),
        weight_update_sharding=3,
        config={"sp_axis": axis},
        description="fsdp over dp + ring attention over sp")


def moe_ruleset(strategy: str, *, axis: str = "ep",
                dp_axis: str = "dp") -> RuleSet:
    """Switch-MoE: expert-stacked ``(L, E, ...)`` FFN leaves shard the
    expert dim; router and every dense leaf replicated; the batch rides
    the flattened (dp, ep) data axis."""
    param_rules = (
        Rule(r"^layers/(w_gate|w_up|w_down)$", (None, axis),
             "expert-stacked (L, E, ...) FFN leaves: shard dim 1 (E)"),
        Rule(r".*", (), "router + dense leaves replicated"),
    )
    return RuleSet(
        strategy=strategy, family="moe", axes=(dp_axis, axis),
        param_rules=param_rules,
        opt_rules=mirror_opt_rules(param_rules),
        batch_rules=(Rule(r".*", ((dp_axis, axis),),
                          "batch over the flattened (dp, ep) axis"),),
        weight_update_sharding=0,
        config={},
        description="switch-moe, experts sharded over ep")


def serve_ruleset(strategy: str, *, axis: str = "tp",
                  paged_kernel: bool = False,
                  step: str = "decode") -> RuleSet:
    """Serving inference steps: tp-sharded weights at rest, inference
    only (no opt state; the KV pool and request vectors ride their own
    specs outside the rule universe).  ``step`` names which engine step
    the strategy lowers — "decode" (one token per slot), "spec_verify"
    (the (B, k+1) speculative verify), or "prefill_flash" (the batched
    flash-kernel prefill chunk) — all three share the family's wire
    choreography: the layer body's two rejoin psums, unrolled over
    depth."""
    base = tp_ruleset(strategy, axis=axis)
    return RuleSet(
        strategy=strategy, family="serve", axes=(axis,),
        param_rules=base.param_rules,
        weight_update_sharding=0,
        config={"paged_kernel": paged_kernel, "step": step},
        description=f"serving {step} over tp"
                    + (", paged-attention kernel" if paged_kernel else ""))


def composable_ruleset(strategy: str, *, dp_axis: str = "dp",
                       fsdp_axis: str = "fsdp", tp_axis: str = "tp",
                       overlap: str = "none") -> RuleSet:
    """The 3-axis dp×fsdp×tp combo of the composable mesh driver
    (``parallel.composable``): Megatron column/row tp roles on the
    projection dim each leaf contracts LAST, named-dim W3 fsdp sharding
    on the other — column-parallel ``(L, in⊘fsdp, out⊘tp)``,
    row-parallel ``(L, in⊘tp, out⊘fsdp)`` — norms and plain leaves
    fsdp-only, the batch jointly over ``(dp, fsdp)`` (both carry data;
    tp sees replicas, exactly as in the 2-D tp family)."""
    col = "|".join(TP_COL_LEAVES)
    row = "|".join(TP_ROW_LEAVES)
    param_rules = (
        Rule(rf"^layers/({col})$", (None, fsdp_axis, tp_axis),
             "column-parallel (L, in, out): fsdp shards in, tp shards "
             "out"),
        Rule(rf"^layers/({row})$", (None, tp_axis, fsdp_axis),
             "row-parallel (L, in, out): tp shards in, fsdp shards out"),
        Rule(r"^layers/", (None, fsdp_axis),
             "other stacked leaves (norms): fsdp shards dim 1"),
        Rule(r".*", (fsdp_axis,),
             "plain leaves (embed, final_norm): fsdp shards dim 0"),
    )
    return RuleSet(
        strategy=strategy, family="composable",
        axes=(dp_axis, fsdp_axis, tp_axis),
        param_rules=param_rules,
        opt_rules=mirror_opt_rules(param_rules),
        batch_rules=(Rule(r".*", ((dp_axis, fsdp_axis),),
                          "batch over the flattened (dp, fsdp) axis, "
                          "replicated over tp"),),
        weight_update_sharding=3,
        config={"overlap": overlap},
        description="composable dp×fsdp×tp (named-dim W3 × megatron tp)"
                    + (f", overlap={overlap}" if overlap != "none"
                       else ""))


def pipeline_ruleset(strategy: str, *, schedule: str | None = None
                     ) -> RuleSet:
    """Pipeline stages are single-device jitted programs: everything
    replicated (within a stage), no mesh collectives at all."""
    return RuleSet(
        strategy=strategy, family="pipeline", axes=(),
        param_rules=(Rule(r".*", (), "stage-local, no mesh"),),
        weight_update_sharding=0,
        config={"schedule": schedule or strategy},
        description="pipeline stage programs (host-mediated transfers)")


RULESETS: dict[str, RuleSet] = {
    "ddp": data_parallel_ruleset("ddp", weight_update_sharding=0),
    "ddp_bucketed": data_parallel_ruleset(
        "ddp_bucketed", weight_update_sharding=0, grad_comm="bucketed"),
    "ddp_q8": data_parallel_ruleset(
        "ddp_q8", weight_update_sharding=0, grad_comm="q8"),
    "zero1": data_parallel_ruleset("zero1", weight_update_sharding=1),
    "zero2": data_parallel_ruleset("zero2", weight_update_sharding=2),
    "zero3": data_parallel_ruleset("zero3", weight_update_sharding=3),
    "fsdp": fsdp_ruleset("fsdp"),
    "fsdp_offload": fsdp_ruleset("fsdp_offload", offload="opt"),
    "fsdp_fp8": fsdp_ruleset("fsdp_fp8", precision="fp8"),
    "fsdp_ring_fused_pallas": fsdp_ruleset(
        "fsdp_ring_fused_pallas", overlap="ring_fused_pallas"),
    "fsdp_ring": fsdp_ruleset("fsdp_ring", overlap="ring"),
    "tp_ring": tp_ruleset("tp_ring", overlap="ring"),
    "tp_q8": tp_ruleset("tp_q8", overlap="q8"),
    "tp": tp_ruleset("tp"),
    "sp": sp_ruleset("sp"),
    "moe": moe_ruleset("moe"),
    "serve_decode": serve_ruleset("serve_decode"),
    "serve_decode_paged_kernel": serve_ruleset(
        "serve_decode_paged_kernel", paged_kernel=True),
    "serve_decode_spec": serve_ruleset(
        "serve_decode_spec", step="spec_verify"),
    "serve_prefill_flash": serve_ruleset(
        "serve_prefill_flash", paged_kernel=True, step="prefill_flash"),
    "gpipe": pipeline_ruleset("gpipe"),
    "1f1b": pipeline_ruleset("1f1b"),
    # composable mesh driver (parallel/composable.py): contracts for
    # these are GENERATED from the rules by contract_gen at import time,
    # never hand-registered — composable_zero1 is the legacy-replay
    # exemplar (same wire choreography as zero1, generated contract),
    # composable_dp_fsdp_tp the genuinely new 3-axis combo.
    "composable_zero1": data_parallel_ruleset(
        "composable_zero1", weight_update_sharding=1),
    "composable_dp_fsdp_tp": composable_ruleset("composable_dp_fsdp_tp"),
}


def ruleset_coverage() -> tuple:
    """RULESETS <-> contract-registry cross-check, the rules twin of
    ``fixtures.contract_coverage``: returns ``(missing, orphans)`` —
    contracted strategies with no RuleSet (analyzer blind spot, error)
    and RuleSets naming no contract (dead rules, error under the
    default-strict gate)."""
    from .contracts import CONTRACTS
    missing = [s for s in CONTRACTS if s not in RULESETS]
    orphans = [s for s in RULESETS if s not in CONTRACTS]
    return missing, orphans


# Module stems (parallel/ + scripts/ drivers + serving) whose step
# functions are covered by a RuleSet — the pitfalls spec-literal lint
# fires only inside these (a hand-rolled PartitionSpec there should be
# derived from the rules instead, or carry a `# spec-ok` pragma).
RULE_COVERED_MODULE_STEMS = frozenset({
    # parallel/ family modules
    "ddp", "zero", "fsdp", "tensor", "sequence", "expert",
    # scripts/ drivers of contracted strategies
    "zero1", "zero2", "zero3", "_zero_driver", "train_fsdp",
    "train_tp", "train_sp", "train_moe", "_2d_driver",
    # composable mesh driver (MeshPlan -> rule-driven step)
    "composable", "train_composable",
    # serving decode step builder
    "engine",
})


# ---------------------------------------------------------------- verdicts

@dataclass(frozen=True)
class ExpectedLeafSpec:
    """One flat step-arg leaf with its rule-derived spec (``spec`` is
    None for roles outside the rule universe, e.g. the serve KV pool)."""
    flat_index: int
    role: str | None
    path: str
    shape: tuple
    spec: Spec | None


def expected_arg_specs(ruleset: RuleSet, args) -> tuple:
    """Flatten a step's example args and attach the rule-derived spec to
    every leaf of a rule-covered role.  Returns ``(expected, reports)``:
    ``expected`` is aligned with the jit flatten order — which is also
    the compiled module's entry ``parameter(i)`` order — and ``reports``
    are the per-role hygiene MatchReports."""
    import jax
    expected: list[ExpectedLeafSpec] = []
    reports: list[MatchReport] = []
    roles = ruleset.arg_roles
    flat_index = 0
    for argnum, arg in enumerate(args):
        role = roles.get(argnum)
        by_path: dict | None = None
        if role is not None:
            report = ruleset.match(role, arg)
            reports.append(report)
            by_path = report.spec_by_path() if report.ok else {}
        flat, _ = jax.tree_util.tree_flatten_with_path(arg)
        for p, leaf in flat:
            path = path_str(p)
            spec = by_path.get(path) if by_path is not None else None
            expected.append(ExpectedLeafSpec(
                flat_index=flat_index,
                role=role,
                path=(f"{role or f'arg{argnum}'}/{path}" if path
                      else (role or f"arg{argnum}")),
                shape=_leaf_shape(leaf),
                spec=spec))
            flat_index += 1
    return expected, reports


def rules_manifest_verdict(strategy: str, *, params=None, opt=None,
                           batch=None) -> dict:
    """The cheap driver-side verdict recorded in ``manifest.json``
    beside the static contract mark: rule hygiene over the live trees
    plus a comparison of each committed leaf's ``NamedSharding`` spec
    against its rule-derived spec.  No lowering, no compile — the
    compiled-HLO drift lint is ``scripts/lint_sharding.py --rules``'s
    job."""
    rs = RULESETS.get(strategy)
    if rs is None:
        return {"strategy": strategy, "ok": False,
                "error": f"no RuleSet registered for {strategy!r}"}
    verdict: dict = {"strategy": strategy, "ok": True, "checked": 0,
                     "mismatches": [], "hygiene": []}
    for role, tree in (("params", params), ("opt", opt),
                       ("batch", batch)):
        if tree is None:
            continue
        report = rs.match(role, tree)
        verdict["hygiene"].append(report.to_dict())
        if not report.ok:
            verdict["ok"] = False
            continue
        by_path = report.spec_by_path()
        for path, leaf in named_leaf_paths(tree):
            sharding = getattr(leaf, "sharding", None)
            spec = getattr(sharding, "spec", None)
            if spec is None:
                continue
            want = by_path.get(path)
            if want is None:
                continue
            ndim = len(_leaf_shape(leaf))
            axis_sizes = dict(getattr(sharding, "mesh").shape) \
                if getattr(sharding, "mesh", None) is not None else {}
            got_tiles = tile_dims(tuple(spec), ndim, axis_sizes)
            want_tiles = tile_dims(want, ndim, axis_sizes)
            verdict["checked"] += 1
            if got_tiles != want_tiles:
                verdict["ok"] = False
                verdict["mismatches"].append(
                    f"{role}/{path}: committed {spec} (tiles "
                    f"{list(got_tiles)}), rules derive "
                    f"{spec_str(want)} (tiles {list(want_tiles)})")
    return verdict
