"""Tiny CPU-mesh builds of every strategy's train step.

One place that knows how to construct a minimal, fast instance of each
strategy exactly the way its ``scripts/`` driver does — shared by the
contract pytest suite and ``scripts/lint_sharding.py`` so "lower the
step and check the choreography" is a one-liner everywhere.

Strategies self-register through :func:`register_strategy`: each builder
function is decorated with the names it knows how to construct, and
``STRATEGIES`` / :func:`build_strategy` are derived from the registry —
adding a strategy is one decorated function, not three parallel edits.
``scripts/lint_sharding.py`` cross-checks the registry against
``contracts.CONTRACTS`` so a builder registered without a collective
contract (or a contract with no builder) fails CI instead of silently
escaping the analyzer.

Everything here is CPU-sized: toy-MLP widths of ~100 and the TINY_LM
transformer at sequence length 32, so the full registry lowers, lints
and runs 3 steps in well under a minute on the 8-device simulated mesh.
"""

from __future__ import annotations

import dataclasses as _dc
from dataclasses import dataclass, field
from typing import Any, Callable

from .contracts import CONTRACTS, ContractContext

# the canonical bucket size for the ddp_bucketed fixture — small enough
# that the toy MLP needs several buckets, so the formula is exercised
FIXTURE_BUCKET_MB = 0.05

# name -> builder; insertion order IS the canonical strategy order
_BUILDERS: dict[str, Callable[..., "StrategyBuild"]] = {}


def register_strategy(*names: str):
    """Register a fixture builder under one or more strategy names.

    The builder is called as ``fn(name, mesh=, scale=, seq=,
    batch_size=)`` and must return a :class:`StrategyBuild`.  Duplicate
    registration is a hard error — two builders claiming one name is a
    merge accident, not a feature."""
    if not names:
        raise ValueError("register_strategy needs at least one name")

    def deco(fn):
        for n in names:
            if n in _BUILDERS:
                raise ValueError(
                    f"strategy {n!r} already registered by "
                    f"{_BUILDERS[n].__name__}")
            _BUILDERS[n] = fn
        return fn
    return deco


def registered_strategies() -> tuple[str, ...]:
    """Every registered strategy name, in registration order."""
    return tuple(_BUILDERS)


def contract_coverage() -> tuple[list[str], list[str]]:
    """Registry ↔ contract cross-check for the lint gate.

    Returns ``(missing_contract, unregistered_contract)``: strategies
    with a fixture builder but no ``CONTRACTS`` entry (an analyzer
    blind spot — error), and contracts with no registered builder (dead
    contract — warning)."""
    regs = registered_strategies()
    missing = [s for s in regs if s not in CONTRACTS]
    orphans = [s for s in CONTRACTS if s not in regs]
    return missing, orphans


@dataclass
class StrategyBuild:
    """A lowered-and-runnable strategy instance plus everything the
    analyzers need to judge it."""
    strategy: str
    step: Callable                    # jitted step fn
    args: tuple                       # example invocation args
    advance: Callable | None          # (args, outputs) -> next args
    mesh: Any                         # jax Mesh or None (pipeline)
    ctx: ContractContext
    donate: bool
    full_param_shapes: set = field(default_factory=set)

    @property
    def contract(self):
        return CONTRACTS[self.strategy]


def _state_advance(args, out):
    """(params, opt, batch) step contract: feed state back, reuse batch."""
    params, opt, loss = out
    return (params, opt, args[2])


@register_strategy("ddp", "ddp_bucketed", "ddp_q8", "zero1", "zero2",
                   "zero3")
def _build_mlp_dp(strategy: str, *, mesh=None, scale: int = 100,
                  seq: int = 32, batch_size: int = 8) -> StrategyBuild:
    """Toy-MLP strategies over a 1-D dp mesh."""
    import jax

    from ..models import zero_toy_mlp
    from ..models.mlp import mse_loss
    from ..parallel import optim
    from ..parallel import make_ddp_train_step
    from ..parallel.zero import (
        make_zero_train_step, init_zero_opt_state, make_zero3_train_step,
        make_zero3_mlp_loss, shard_params_zero3)
    from ..utils import make_mesh, set_seed
    from .hlo_lint import param_shapes

    key = set_seed(0)
    mesh = mesh or make_mesh(register=False)
    params = zero_toy_mlp(key, scale=scale)
    width = 10_000 // scale
    kx, ky = jax.random.split(key)
    b = (jax.random.normal(kx, (batch_size, width)),
         jax.random.normal(ky, (batch_size, width)))
    shapes = param_shapes(params, min_numel=256)
    extra = {"bucket_mb": FIXTURE_BUCKET_MB} \
        if strategy in ("ddp_bucketed", "ddp_q8") else {}
    ctx = ContractContext.capture(params=params, mesh=mesh,
                                  n_layers=len(params), **extra)
    if strategy in ("ddp", "ddp_bucketed", "ddp_q8"):
        step = make_ddp_train_step(
            mse_loss,
            lambda g, s, p: optim.sgd_update(g, s, p, lr=1e-3),
            mesh, "dp",
            bucket_mb=FIXTURE_BUCKET_MB
            if strategy in ("ddp_bucketed", "ddp_q8") else None,
            quantize_grads=strategy == "ddp_q8")
        args = (params, optim.sgd_init(params), b)
    elif strategy in ("zero1", "zero2"):
        step = make_zero_train_step(mse_loss, mesh, "dp",
                                    stage=int(strategy[-1]))
        args = (params, init_zero_opt_state(params, mesh, "dp"), b)
    else:
        layer_shapes = [{k: v.shape for k, v in layer.items()}
                        for layer in params]
        step = make_zero3_train_step(
            make_zero3_mlp_loss(layer_shapes, "dp"), mesh, "dp")
        args = (shard_params_zero3(params, mesh, "dp"),
                init_zero_opt_state(params, mesh, "dp"), b)
    return StrategyBuild(strategy, step, args, _state_advance, mesh,
                         ctx, donate=True, full_param_shapes=shapes)


@register_strategy("fsdp", "fsdp_ring", "fsdp_fp8",
                   "fsdp_ring_fused_pallas", "fsdp_offload", "tp",
                   "tp_ring", "tp_q8", "sp", "moe")
def _build_transformer(strategy: str, *, mesh=None, scale: int = 100,
                       seq: int = 32,
                       batch_size: int = 8) -> StrategyBuild:
    """TINY_LM transformer strategies over 1-D dp or dp × {tp,sp,ep}."""
    import jax
    import jax.numpy as jnp

    from ..models import transformer as T
    from ..parallel import fsdp, sequence, tensor, expert
    from ..utils import make_mesh, set_seed
    from .hlo_lint import param_shapes

    key = set_seed(0)
    n_dev = len(jax.devices())
    mcfg = T.TINY_LM
    second_axis = {"fsdp": None, "fsdp_ring": None, "fsdp_fp8": None,
                   "fsdp_ring_fused_pallas": None,
                   "fsdp_offload": None, "tp": "tp",
                   "tp_ring": "tp", "tp_q8": "tp", "sp": "sp",
                   "moe": "ep"}[strategy]
    if mesh is None:
        if second_axis is None:
            mesh = make_mesh(register=False)
        else:
            if n_dev < 4:
                raise RuntimeError(
                    f"{strategy} fixture needs >= 4 devices "
                    f"(have {n_dev})")
            mesh = make_mesh({"dp": n_dev // 2, second_axis: 2},
                             register=False)
    if strategy == "moe":
        mcfg = _dc.replace(mcfg, n_experts=4,
                           moe_ffn=max(mcfg.intermediate_size // 4, 8))
    params = T.init_params(key, mcfg)
    shapes = param_shapes(params, min_numel=1024)
    ctx = ContractContext.capture(params=params, mesh=mesh,
                                  n_layers=mcfg.num_hidden_layers)
    if strategy in ("fsdp", "fsdp_ring", "fsdp_fp8",
                    "fsdp_ring_fused_pallas"):
        if strategy == "fsdp_fp8":
            # the fp8 precision leg: e4m3 fwd / e5m2 bwd scaled matmuls
            # in the dense seam — same gather choreography as fsdp
            mcfg = _dc.replace(mcfg, matmul_precision="fp8")
        overlap = {"fsdp_ring": "ring",
                   "fsdp_ring_fused_pallas": "ring_fused_pallas"}.get(
                       strategy, "none")
        shards = fsdp.shard_params_fsdp(params, mesh)
        step = fsdp.make_fsdp_train_step(shards, mcfg, mesh,
                                         overlap=overlap)
    elif strategy == "fsdp_offload":
        # host-offloaded optimizer state: park the Adam moments in
        # pinned host memory (identity placement on the CPU sim) and
        # declare the resulting transfer counts into the contract ctx
        from ..memory_plan import offload_tree, plan_offload
        shards = fsdp.shard_params_fsdp(params, mesh)
        opt0 = fsdp.init_fsdp_opt_state(shards)
        oplan = plan_offload("opt", opt0)
        if oplan.supported:
            opt0 = offload_tree(opt0)
        step = fsdp.make_fsdp_train_step(shards, mcfg, mesh,
                                         offload="opt")
        ctx = ContractContext.capture(
            params=params, mesh=mesh,
            n_layers=mcfg.num_hidden_layers,
            offload=oplan.to_dict())
        probe = (jnp.zeros((batch_size, seq), jnp.int32),) * 2
        return StrategyBuild(strategy, step, (shards, opt0, probe),
                             _state_advance, mesh, ctx, donate=True,
                             full_param_shapes=shapes)
    elif strategy == "sp":
        shards = fsdp.shard_params_fsdp(params, mesh, "dp")
        step = sequence.make_sp_train_step(shards, mcfg, mesh)
    elif strategy in ("tp", "tp_ring", "tp_q8"):
        shards = tensor.shard_params_tp(params, mesh)
        step = tensor.make_tp_train_step(
            shards, mcfg, mesh,
            overlap={"tp_ring": "ring", "tp_q8": "q8"}.get(
                strategy, "none"))
    else:
        shards = expert.shard_moe_lm_params(params, mesh)
        step = expert.make_moe_lm_train_step(shards, mcfg, mesh)
    opt = fsdp.init_fsdp_opt_state(shards)
    probe = (jnp.zeros((batch_size, seq), jnp.int32),) * 2
    return StrategyBuild(strategy, step, (shards, opt, probe),
                         _state_advance, mesh, ctx, donate=True,
                         full_param_shapes=shapes)


@register_strategy("composable_zero1", "composable_dp_fsdp_tp")
def _build_composable(strategy: str, *, mesh=None, scale: int = 100,
                      seq: int = 32,
                      batch_size: int = 8) -> StrategyBuild:
    """MeshPlan-driven builds through ``make_composable_train_step`` —
    the generated-contract strategies.  ``composable_zero1`` is the toy
    MLP at W1 over flat dp (zero1's bitwise twin through the composable
    surface); ``composable_dp_fsdp_tp`` is TINY_LM on the 3-axis
    dp×fsdp×tp mesh, placement from its RuleSet."""
    import jax
    import jax.numpy as jnp

    from ..models import transformer as T, zero_toy_mlp
    from ..models.mlp import mse_loss
    from ..parallel.composable import MeshPlan, make_composable_train_step
    from ..utils import make_mesh, set_seed
    from .hlo_lint import param_shapes

    key = set_seed(0)
    n_dev = len(jax.devices())
    if strategy == "composable_zero1":
        mesh = mesh or make_mesh(register=False)
        params = zero_toy_mlp(key, scale=scale)
        plan = MeshPlan(dp=int(mesh.shape["dp"]), w=1)
        build = make_composable_train_step(params, plan, mesh,
                                           loss_fn=mse_loss)
        width = 10_000 // scale
        kx, ky = jax.random.split(key)
        b = (jax.random.normal(kx, (batch_size, width)),
             jax.random.normal(ky, (batch_size, width)))
        shapes = param_shapes(params, min_numel=256)
        ctx = ContractContext.capture(params=params, mesh=mesh,
                                      n_layers=len(params),
                                      **build.contract_kwargs)
    else:
        if mesh is None:
            if n_dev < 8:
                raise RuntimeError(
                    f"{strategy} fixture needs >= 8 devices "
                    f"(have {n_dev})")
            mesh = make_mesh({"dp": n_dev // 4, "fsdp": 2, "tp": 2},
                             register=False)
        mcfg = T.TINY_LM
        params = T.init_params(key, mcfg)
        plan = MeshPlan(dp=int(mesh.shape["dp"]),
                        fsdp=int(mesh.shape["fsdp"]),
                        tp=int(mesh.shape["tp"]))
        build = make_composable_train_step(params, plan, mesh,
                                           model_cfg=mcfg)
        b = (jnp.zeros((batch_size, seq), jnp.int32),) * 2
        shapes = param_shapes(params, min_numel=1024)
        ctx = ContractContext.capture(params=params, mesh=mesh,
                                      **build.contract_kwargs)
    return StrategyBuild(strategy, build.step,
                         (build.params, build.opt_state, b),
                         _state_advance, mesh, ctx, donate=True,
                         full_param_shapes=shapes)


@register_strategy("serve_decode", "serve_decode_paged_kernel")
def _build_serve_decode(strategy: str, *, mesh=None, scale: int = 100,
                        seq: int = 32,
                        batch_size: int = 8) -> StrategyBuild:
    """Serving decode step over dp × tp (``_paged_kernel``: attention
    through the Pallas paged decode kernel, bitwise choreography twin)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models import transformer as T
    from ..models.generate import _decode_cfg
    from ..parallel import tensor
    from ..serving import PagedKVPool, make_serve_decode_step
    from ..utils import make_mesh, set_seed
    from .hlo_lint import param_shapes

    key = set_seed(0)
    n_dev = len(jax.devices())
    mcfg = T.TINY_LM
    if mesh is None:
        if n_dev < 4:
            raise RuntimeError(
                f"serve_decode fixture needs >= 4 devices "
                f"(have {n_dev})")
        mesh = make_mesh({"dp": n_dev // 2, "tp": 2}, register=False)
    params = T.init_params(key, mcfg)
    shapes = param_shapes(params, min_numel=1024)
    ctx = ContractContext.capture(params=params, mesh=mesh,
                                  n_layers=mcfg.num_hidden_layers)
    shards = tensor.shard_params_tp(params, mesh)
    page_size, pages_per = 8, 4
    pool = PagedKVPool(_decode_cfg(mcfg),
                       batch_size * pages_per + 1, page_size,
                       mesh=mesh)
    step = make_serve_decode_step(
        mcfg, shards, mesh=mesh, pool_spec=pool.spec,
        paged_kernel=strategy == "serve_decode_paged_kernel")
    pages = jnp.asarray(np.arange(
        1, batch_size * pages_per + 1,
        dtype=np.int32).reshape(batch_size, pages_per))
    args = (pool.bufs, shards, pages,
            jnp.zeros((batch_size,), jnp.int32),       # tokens
            jnp.zeros((batch_size,), jnp.int32),       # lengths
            jnp.full((batch_size,), page_size * pages_per - 1,
                     jnp.int32),                       # stop_at
            jnp.ones((batch_size,), bool))             # active
    # outputs: (nxt, new_len, new_active, bufs, occ) — feed the
    # donated pool and the token/length/active chain back in
    advance = lambda args, out: (out[3], args[1], args[2], out[0],
                                 out[1], args[5], out[2])
    return StrategyBuild(strategy, step, args, advance, mesh, ctx,
                         donate=True, full_param_shapes=shapes)


@register_strategy("serve_decode_spec", "serve_prefill_flash")
def _build_serve_frontier(strategy: str, *, mesh=None, scale: int = 100,
                          seq: int = 32,
                          batch_size: int = 8) -> StrategyBuild:
    """The PR-18 serving steps over dp × tp: the speculative (B, k+1)
    verify forward and the batched flash-kernel prefill chunk.  Both
    share serve_decode's wire choreography — 2 rejoin psums per
    unrolled layer over tp, nothing else."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models import transformer as T
    from ..models.generate import _decode_cfg
    from ..parallel import tensor
    from ..serving import (PagedKVPool, make_serve_prefill_batch_step,
                           make_serve_spec_verify_step)
    from ..utils import make_mesh, set_seed
    from .hlo_lint import param_shapes

    key = set_seed(0)
    n_dev = len(jax.devices())
    mcfg = T.TINY_LM
    if mesh is None:
        if n_dev < 4:
            raise RuntimeError(
                f"{strategy} fixture needs >= 4 devices "
                f"(have {n_dev})")
        mesh = make_mesh({"dp": n_dev // 2, "tp": 2}, register=False)
    params = T.init_params(key, mcfg)
    shapes = param_shapes(params, min_numel=1024)
    ctx = ContractContext.capture(params=params, mesh=mesh,
                                  n_layers=mcfg.num_hidden_layers)
    shards = tensor.shard_params_tp(params, mesh)
    page_size, pages_per = 8, 4
    pool = PagedKVPool(_decode_cfg(mcfg),
                       batch_size * pages_per + 1, page_size,
                       mesh=mesh)
    pages = jnp.asarray(np.arange(
        1, batch_size * pages_per + 1,
        dtype=np.int32).reshape(batch_size, pages_per))
    if strategy == "serve_decode_spec":
        k = 3
        step = make_serve_spec_verify_step(
            mcfg, shards, mesh=mesh, pool_spec=pool.spec)
        args = (pool.bufs, shards, pages,
                jnp.zeros((batch_size, k + 1), jnp.int32),  # proposals
                jnp.zeros((batch_size,), jnp.int32),        # lengths
                jnp.full((batch_size,), page_size * pages_per - 1,
                         jnp.int32),                        # stop_at
                jnp.ones((batch_size,), bool))              # active
        # outputs: (greedy, bufs, occ) — the host accept/rollback jit
        # sits between bursts, so the fixture just recycles the pool
        advance = lambda args, out: (out[1],) + args[1:]
    else:
        chunk = 16
        step = make_serve_prefill_batch_step(
            mcfg, shards, mesh=mesh, pool_spec=pool.spec,
            flash_prefill=True)
        ids = jnp.asarray(np.random.default_rng(0).integers(
            1, mcfg.vocab_size, size=(batch_size, chunk),
            dtype=np.int32))
        args = (pool.bufs, shards, pages, ids,
                jnp.zeros((batch_size,), jnp.int32),        # chunk pos
                jnp.full((batch_size,), chunk, jnp.int32))  # prompt len
        # outputs: (first_tok, bufs)
        advance = lambda args, out: (out[1],) + args[1:]
    return StrategyBuild(strategy, step, args, advance, mesh, ctx,
                         donate=True, full_param_shapes=shapes)


@register_strategy("gpipe", "1f1b")
def _build_pipeline(strategy: str, *, mesh=None, scale: int = 100,
                    seq: int = 32,
                    batch_size: int = 8) -> StrategyBuild:
    """Pipeline schedules: single-device stage programs."""
    import jax

    from ..models import pp_toy_mlp
    from ..models.mlp import PP_TOY_SIZES
    from ..parallel.pipeline import build_pipeline
    from ..utils import set_seed

    key = set_seed(0)
    params = pp_toy_mlp(key)
    stages = build_pipeline(params, 2)
    x = jax.random.normal(key, (batch_size, PP_TOY_SIZES[0]))
    ctx = ContractContext.capture(params=stages[0].params,
                                  n_layers=len(params))
    return StrategyBuild(strategy, stages[0].fwd,
                         (stages[0].params, x),
                         None, None, ctx, donate=False)


# the public, ordered tuple every caller keys on — derived from the
# registry so it can never drift from what build_strategy dispatches
STRATEGIES = registered_strategies()


def build_strategy(strategy: str, *, mesh=None, scale: int = 100,
                   seq: int = 32, batch_size: int = 8) -> StrategyBuild:
    """Construct the named strategy's step the way its script does.

    Dispatches to the :func:`register_strategy`-decorated builder.
    ``mesh`` defaults to a fresh mesh of the canonical shape for that
    strategy over all visible devices (1-D ``dp``, or ``{dp: n/2, x: 2}``
    for the 2-D strategies)."""
    try:
        builder = _BUILDERS[strategy]
    except KeyError:
        raise KeyError(
            f"unknown strategy {strategy!r}; have {STRATEGIES}") from None
    return builder(strategy, mesh=mesh, scale=scale, seq=seq,
                   batch_size=batch_size)
