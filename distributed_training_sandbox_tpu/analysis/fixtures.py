"""Tiny CPU-mesh builds of every strategy's train step.

One place that knows how to construct a minimal, fast instance of each
strategy exactly the way its ``scripts/`` driver does — shared by the
contract pytest suite and ``scripts/lint_sharding.py`` so "lower the
step and check the choreography" is a one-liner everywhere.

Everything here is CPU-sized: toy-MLP widths of ~100 and the TINY_LM
transformer at sequence length 32, so the full registry lowers, lints
and runs 3 steps in well under a minute on the 8-device simulated mesh.
"""

from __future__ import annotations

import dataclasses as _dc
from dataclasses import dataclass, field
from typing import Any, Callable

from .contracts import CONTRACTS, ContractContext

STRATEGIES = ("ddp", "ddp_bucketed", "ddp_q8", "zero1", "zero2", "zero3",
              "fsdp", "fsdp_ring", "fsdp_offload", "tp", "tp_ring", "sp",
              "moe", "serve_decode", "gpipe", "1f1b")

# the canonical bucket size for the ddp_bucketed fixture — small enough
# that the toy MLP needs several buckets, so the formula is exercised
FIXTURE_BUCKET_MB = 0.05


@dataclass
class StrategyBuild:
    """A lowered-and-runnable strategy instance plus everything the
    analyzers need to judge it."""
    strategy: str
    step: Callable                    # jitted step fn
    args: tuple                       # example invocation args
    advance: Callable | None          # (args, outputs) -> next args
    mesh: Any                         # jax Mesh or None (pipeline)
    ctx: ContractContext
    donate: bool
    full_param_shapes: set = field(default_factory=set)

    @property
    def contract(self):
        return CONTRACTS[self.strategy]


def _state_advance(args, out):
    """(params, opt, batch) step contract: feed state back, reuse batch."""
    params, opt, loss = out
    return (params, opt, args[2])


def build_strategy(strategy: str, *, mesh=None, scale: int = 100,
                   seq: int = 32, batch_size: int = 8) -> StrategyBuild:
    """Construct the named strategy's step the way its script does.

    ``mesh`` defaults to a fresh mesh of the canonical shape for that
    strategy over all visible devices (1-D ``dp``, or ``{dp: n/2, x: 2}``
    for the 2-D strategies)."""
    import jax
    import jax.numpy as jnp

    from ..models import transformer as T
    from ..models import zero_toy_mlp, pp_toy_mlp
    from ..models.mlp import mse_loss, PP_TOY_SIZES
    from ..parallel import fsdp, optim, sequence, tensor, expert
    from ..parallel import make_ddp_train_step
    from ..parallel.zero import (
        make_zero_train_step, init_zero_opt_state, make_zero3_train_step,
        make_zero3_mlp_loss, shard_params_zero3)
    from ..utils import make_mesh, set_seed
    from .hlo_lint import param_shapes

    if strategy not in STRATEGIES:
        raise KeyError(f"unknown strategy {strategy!r}; have {STRATEGIES}")
    key = set_seed(0)
    n_dev = len(jax.devices())

    # ---- toy-MLP strategies over a 1-D dp mesh -------------------------
    if strategy in ("ddp", "ddp_bucketed", "ddp_q8", "zero1", "zero2",
                    "zero3"):
        mesh = mesh or make_mesh(register=False)
        params = zero_toy_mlp(key, scale=scale)
        width = 10_000 // scale
        kx, ky = jax.random.split(key)
        b = (jax.random.normal(kx, (batch_size, width)),
             jax.random.normal(ky, (batch_size, width)))
        shapes = param_shapes(params, min_numel=256)
        extra = {"bucket_mb": FIXTURE_BUCKET_MB} \
            if strategy in ("ddp_bucketed", "ddp_q8") else {}
        ctx = ContractContext.capture(params=params, mesh=mesh,
                                      n_layers=len(params), **extra)
        if strategy in ("ddp", "ddp_bucketed", "ddp_q8"):
            step = make_ddp_train_step(
                mse_loss,
                lambda g, s, p: optim.sgd_update(g, s, p, lr=1e-3),
                mesh, "dp",
                bucket_mb=FIXTURE_BUCKET_MB
                if strategy in ("ddp_bucketed", "ddp_q8") else None,
                quantize_grads=strategy == "ddp_q8")
            args = (params, optim.sgd_init(params), b)
        elif strategy in ("zero1", "zero2"):
            step = make_zero_train_step(mse_loss, mesh, "dp",
                                        stage=int(strategy[-1]))
            args = (params, init_zero_opt_state(params, mesh, "dp"), b)
        else:
            layer_shapes = [{k: v.shape for k, v in layer.items()}
                            for layer in params]
            step = make_zero3_train_step(
                make_zero3_mlp_loss(layer_shapes, "dp"), mesh, "dp")
            args = (shard_params_zero3(params, mesh, "dp"),
                    init_zero_opt_state(params, mesh, "dp"), b)
        return StrategyBuild(strategy, step, args, _state_advance, mesh,
                             ctx, donate=True, full_param_shapes=shapes)

    # ---- transformer strategies ----------------------------------------
    if strategy in ("fsdp", "fsdp_ring", "fsdp_offload", "tp", "tp_ring",
                    "sp", "moe"):
        mcfg = T.TINY_LM
        second_axis = {"fsdp": None, "fsdp_ring": None,
                       "fsdp_offload": None, "tp": "tp",
                       "tp_ring": "tp", "sp": "sp", "moe": "ep"}[strategy]
        if mesh is None:
            if second_axis is None:
                mesh = make_mesh(register=False)
            else:
                if n_dev < 4:
                    raise RuntimeError(
                        f"{strategy} fixture needs >= 4 devices "
                        f"(have {n_dev})")
                mesh = make_mesh({"dp": n_dev // 2, second_axis: 2},
                                 register=False)
        if strategy == "moe":
            mcfg = _dc.replace(mcfg, n_experts=4,
                               moe_ffn=max(mcfg.intermediate_size // 4, 8))
        params = T.init_params(key, mcfg)
        shapes = param_shapes(params, min_numel=1024)
        ctx = ContractContext.capture(params=params, mesh=mesh,
                                      n_layers=mcfg.num_hidden_layers)
        if strategy in ("fsdp", "fsdp_ring"):
            shards = fsdp.shard_params_fsdp(params, mesh)
            step = fsdp.make_fsdp_train_step(
                shards, mcfg, mesh,
                overlap="ring" if strategy == "fsdp_ring" else "none")
        elif strategy == "fsdp_offload":
            # host-offloaded optimizer state: park the Adam moments in
            # pinned host memory (identity placement on the CPU sim) and
            # declare the resulting transfer counts into the contract ctx
            from ..memory_plan import offload_tree, plan_offload
            shards = fsdp.shard_params_fsdp(params, mesh)
            opt0 = fsdp.init_fsdp_opt_state(shards)
            oplan = plan_offload("opt", opt0)
            if oplan.supported:
                opt0 = offload_tree(opt0)
            step = fsdp.make_fsdp_train_step(shards, mcfg, mesh,
                                             offload="opt")
            ctx = ContractContext.capture(
                params=params, mesh=mesh,
                n_layers=mcfg.num_hidden_layers,
                offload=oplan.to_dict())
            probe = (jnp.zeros((batch_size, seq), jnp.int32),) * 2
            return StrategyBuild(strategy, step, (shards, opt0, probe),
                                 _state_advance, mesh, ctx, donate=True,
                                 full_param_shapes=shapes)
        elif strategy == "sp":
            shards = fsdp.shard_params_fsdp(params, mesh, "dp")
            step = sequence.make_sp_train_step(shards, mcfg, mesh)
        elif strategy in ("tp", "tp_ring"):
            shards = tensor.shard_params_tp(params, mesh)
            step = tensor.make_tp_train_step(
                shards, mcfg, mesh,
                overlap="ring" if strategy == "tp_ring" else "none")
        else:
            shards = expert.shard_moe_lm_params(params, mesh)
            step = expert.make_moe_lm_train_step(shards, mcfg, mesh)
        opt = fsdp.init_fsdp_opt_state(shards)
        probe = (jnp.zeros((batch_size, seq), jnp.int32),) * 2
        return StrategyBuild(strategy, step, (shards, opt, probe),
                             _state_advance, mesh, ctx, donate=True,
                             full_param_shapes=shapes)

    # ---- serving decode step over dp × tp ------------------------------
    if strategy == "serve_decode":
        from ..models.generate import _decode_cfg
        from ..serving import PagedKVPool, make_serve_decode_step
        mcfg = T.TINY_LM
        if mesh is None:
            if n_dev < 4:
                raise RuntimeError(
                    f"serve_decode fixture needs >= 4 devices "
                    f"(have {n_dev})")
            mesh = make_mesh({"dp": n_dev // 2, "tp": 2}, register=False)
        params = T.init_params(key, mcfg)
        shapes = param_shapes(params, min_numel=1024)
        ctx = ContractContext.capture(params=params, mesh=mesh,
                                      n_layers=mcfg.num_hidden_layers)
        shards = tensor.shard_params_tp(params, mesh)
        page_size, pages_per = 8, 4
        pool = PagedKVPool(_decode_cfg(mcfg),
                           batch_size * pages_per + 1, page_size,
                           mesh=mesh)
        step = make_serve_decode_step(mcfg, shards, mesh=mesh,
                                      pool_spec=pool.spec)
        import numpy as np
        pages = jnp.asarray(np.arange(
            1, batch_size * pages_per + 1,
            dtype=np.int32).reshape(batch_size, pages_per))
        args = (pool.bufs, shards, pages,
                jnp.zeros((batch_size,), jnp.int32),       # tokens
                jnp.zeros((batch_size,), jnp.int32),       # lengths
                jnp.full((batch_size,), page_size * pages_per - 1,
                         jnp.int32),                       # stop_at
                jnp.ones((batch_size,), bool))             # active
        # outputs: (nxt, new_len, new_active, bufs, occ) — feed the
        # donated pool and the token/length/active chain back in
        advance = lambda args, out: (out[3], args[1], args[2], out[0],
                                     out[1], args[5], out[2])
        return StrategyBuild(strategy, step, args, advance, mesh, ctx,
                             donate=True, full_param_shapes=shapes)

    # ---- pipeline schedules: single-device stage programs --------------
    from ..parallel.pipeline import build_pipeline
    params = pp_toy_mlp(key)
    stages = build_pipeline(params, 2)
    x = jax.random.normal(key, (batch_size, PP_TOY_SIZES[0]))
    ctx = ContractContext.capture(params=stages[0].params,
                                  n_layers=len(params))
    return StrategyBuild(strategy, stages[0].fwd,
                         (stages[0].params, x),
                         None, None, ctx, donate=False)
