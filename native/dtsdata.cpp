// Native data engine for the packed-LM pipeline.
//
// The reference rides torch's C++-backed DataLoader for its host-side
// data path; this is the TPU build's native equivalent for the pieces
// that are actually hot on the host: the seeded Zipfian synthetic token
// stream (alias-method sampling — numpy's choice() over a 128k-vocab
// probability vector does a binary search per token), the window
// packer, and epoch shuffles.  Exposed as a plain C ABI consumed via
// ctypes (distributed_training_sandbox_tpu/data/native.py) — no
// pybind11 dependency.
//
// Determinism contract: every function is a pure function of its
// arguments incl. the seed (splitmix64 → xoshiro256**), identical
// across runs and hosts.  The native Zipf stream is NOT bit-identical
// to numpy's Generator.choice — it is its own documented deterministic
// stream (tests pin determinism and distribution shape, and exact
// equality for the packer, which is pure arithmetic).
//
// Build: g++ -O3 -shared -fPIC -o libdtsdata.so dtsdata.cpp
// (data/native.py does this on first use and caches the .so).

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// ----------------------------------------------------------------- rng

static inline uint64_t splitmix64(uint64_t &x) {
  x += 0x9e3779b97f4a7c15ULL;
  uint64_t z = x;
  z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
  z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
  return z ^ (z >> 31);
}

struct Xoshiro {
  uint64_t s[4];
  explicit Xoshiro(uint64_t seed) {
    for (int i = 0; i < 4; i++) s[i] = splitmix64(seed);
  }
  static inline uint64_t rotl(uint64_t x, int k) {
    return (x << k) | (x >> (64 - k));
  }
  inline uint64_t next() {
    uint64_t result = rotl(s[1] * 5, 7) * 9;
    uint64_t t = s[1] << 17;
    s[2] ^= s[0];
    s[3] ^= s[1];
    s[1] ^= s[2];
    s[0] ^= s[3];
    s[2] ^= t;
    s[3] = rotl(s[3], 45);
    return result;
  }
  // uniform double in [0, 1)
  inline double u01() { return (next() >> 11) * 0x1.0p-53; }
  // uniform integer in [0, n)
  inline uint64_t below(uint64_t n) { return next() % n; }
};

// ------------------------------------------------- zipf via alias table

// Fill out[0..n) with token ids in [0, vocab) drawn from the Zipfian
// unigram distribution p_i ∝ 1/(i+1) (the same law
// data/packing.py:synthetic_token_stream uses).  Walker alias method:
// O(vocab) build, O(1) per sample.
void dts_zipf_fill(int32_t *out, int64_t n, int32_t vocab, uint64_t seed) {
  std::vector<double> prob(vocab);
  double norm = 0.0;
  for (int32_t i = 0; i < vocab; i++) {
    prob[i] = 1.0 / (double)(i + 1);
    norm += prob[i];
  }
  // scaled probabilities (mean 1) and the alias tables
  std::vector<double> q(vocab);
  std::vector<int32_t> alias(vocab, 0);
  std::vector<int32_t> small, large;
  small.reserve(vocab);
  large.reserve(vocab);
  for (int32_t i = 0; i < vocab; i++) {
    q[i] = prob[i] / norm * (double)vocab;
    (q[i] < 1.0 ? small : large).push_back(i);
  }
  while (!small.empty() && !large.empty()) {
    int32_t s = small.back(); small.pop_back();
    int32_t l = large.back(); large.pop_back();
    alias[s] = l;
    q[l] = (q[l] + q[s]) - 1.0;
    (q[l] < 1.0 ? small : large).push_back(l);
  }
  while (!large.empty()) { q[large.back()] = 1.0; large.pop_back(); }
  while (!small.empty()) { q[small.back()] = 1.0; small.pop_back(); }

  Xoshiro rng(seed);
  for (int64_t i = 0; i < n; i++) {
    int32_t col = (int32_t)rng.below((uint64_t)vocab);
    out[i] = (rng.u01() < q[col]) ? col : alias[col];
  }
}

// --------------------------------------------------------- window pack

// Concatenated stream → (inputs, labels), both (n_windows, seq_len),
// stride seq_len+1, ragged tail dropped — byte-for-byte the rule of
// data/packing.py:pack_tokens (reference fsdp/utils.py:58-89).
// Returns n_windows.  inputs/labels must hold n_windows*seq_len ints.
int64_t dts_pack_windows(const int32_t *stream, int64_t n_tokens,
                         int64_t seq_len, int32_t *inputs,
                         int32_t *labels) {
  const int64_t window = seq_len + 1;
  const int64_t n_windows = n_tokens / window;
  for (int64_t w = 0; w < n_windows; w++) {
    const int32_t *src = stream + w * window;
    std::memcpy(inputs + w * seq_len, src, seq_len * sizeof(int32_t));
    std::memcpy(labels + w * seq_len, src + 1, seq_len * sizeof(int32_t));
  }
  return n_windows;
}

// ------------------------------------------------------- epoch shuffle

// out[0..n) = a seeded Fisher–Yates permutation of [0, n).
void dts_shuffle_indices(int64_t *out, int64_t n, uint64_t seed) {
  for (int64_t i = 0; i < n; i++) out[i] = i;
  Xoshiro rng(seed);
  for (int64_t i = n - 1; i > 0; i--) {
    int64_t j = (int64_t)rng.below((uint64_t)(i + 1));
    int64_t t = out[i]; out[i] = out[j]; out[j] = t;
  }
}

}  // extern "C"
